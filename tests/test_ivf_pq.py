"""IVF-PQ: recall-threshold tests vs brute force (reference pattern
test/neighbors/ann_ivf_pq.cuh per-config min_recall gates)."""

import numpy as np
import pytest

from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors import knn
from raft_tpu.neighbors.ivf_pq import (
    CodebookKind,
    IndexParams,
    SearchParams,
    build,
    search,
)


def make_data(n=4000, dim=32, n_queries=64, seed=0, clusters=50):
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 5, (clusters, dim))
    assign = rng.integers(0, clusters, n)
    x = (centers[assign] + rng.normal(0, 1, (n, dim))).astype(np.float32)
    q = (centers[rng.integers(0, clusters, n_queries)] +
         rng.normal(0, 1, (n_queries, dim))).astype(np.float32)
    return x, q


def recall(found, truth):
    hits = 0
    for f, t in zip(np.asarray(found), np.asarray(truth)):
        hits += len(set(f.tolist()) & set(t.tolist()))
    return hits / truth.size


@pytest.mark.parametrize("pq_bits,min_recall", [(8, 0.85), (6, 0.75),
                                                (4, 0.55)])
def test_ivf_pq_recall_pq_bits(pq_bits, min_recall):
    x, q = make_data()
    k = 10
    idx = build(IndexParams(n_lists=50, pq_bits=pq_bits, pq_dim=16,
                            seed=5), x)
    d, i = search(SearchParams(n_probes=20), idx, q, k)
    _, ti = knn(x, q, k, DistanceType.L2Expanded)
    r = recall(i, np.array(ti))
    assert r >= min_recall, f"recall {r} < {min_recall} at pq_bits={pq_bits}"


def test_ivf_pq_per_cluster_codebooks():
    x, q = make_data(n=3000, dim=24)
    idx = build(IndexParams(n_lists=40, pq_bits=8, pq_dim=12,
                            codebook_kind=CodebookKind.PER_CLUSTER, seed=2), x)
    assert idx.codebooks.shape == (40, 256, 2)
    d, i = search(SearchParams(n_probes=16), idx, q, 10)
    _, ti = knn(x, q, 10, DistanceType.L2Expanded)
    assert recall(i, np.array(ti)) >= 0.8


def test_ivf_pq_per_cluster_subcap_sampling_seed_stable():
    """ADVICE r5 leftover (ISSUE 7 satellite): sub-cap clusters fill their
    codebook-training sample with draws from an INDEPENDENT random stream
    (``rng_fill``), not cyclic repetition of one permutation.  Contract:
    (a) same build seed → bit-identical codebooks (seed-stable);
    (b) a different seed re-draws the sub-cap fill → different codebooks;
    (c) the fill indices are not the deterministic cyclic ``j % count``
    pattern — for a tiny pool, consecutive sample slots must not simply
    tile the permuted pool period-``count``."""
    from raft_tpu.neighbors.ivf_pq import _train_codebooks_cluster_host

    import jax

    rng = np.random.default_rng(0)
    n, n_lists, pq_dim, ds = 120, 6, 4, 3
    resid = rng.normal(0, 1, (n, pq_dim * ds)).astype(np.float32)
    labels = rng.integers(0, n_lists, n).astype(np.int32)
    args = (resid, labels, n_lists, pq_dim, 16, 3)
    cb1 = np.asarray(_train_codebooks_cluster_host(
        jax.random.PRNGKey(7), *args))
    cb2 = np.asarray(_train_codebooks_cluster_host(
        jax.random.PRNGKey(7), *args))
    assert np.array_equal(cb1, cb2), "same key must reproduce codebooks"
    cb3 = np.asarray(_train_codebooks_cluster_host(
        jax.random.PRNGKey(8), *args))
    assert not np.array_equal(cb1, cb3), "independent fill must re-draw"
    # (c) structural, on the extracted fill helper: a sub-cap pool's
    # sample positions are NOT the deterministic cyclic ``j % count``
    # tiling, cover the pool, and pools >= cap keep the exact r5
    # without-replacement arange
    from raft_tpu.neighbors.ivf_pq import _cluster_sample_take

    counts = np.array([2, 100, 64], np.int64)
    cap = 64
    take = _cluster_sample_take(counts, cap,
                                np.random.default_rng(3))
    sub = take[0] % counts[0]
    assert not np.array_equal(sub, np.arange(cap) % counts[0]), \
        "sub-cap fill is still the cyclic permutation tiling"
    # coverage: the first `count` slots are the without-replacement
    # permutation prefix, so every pool member still trains exactly once
    # before any random repeat (review hardening: iid fill over the WHOLE
    # sample would drop ~1/e of a near-cap pool from training)
    np.testing.assert_array_equal(take[0][:2], np.arange(2))
    assert set(sub.tolist()) == {0, 1}, "fill must still cover the pool"
    np.testing.assert_array_equal(take[1], np.arange(cap))
    np.testing.assert_array_equal(take[2], np.arange(cap))


def test_ivf_pq_rotation_non_divisible():
    # dim not a multiple of pq_dim → random rotation into rot_dim
    x, q = make_data(n=2000, dim=30)
    idx = build(IndexParams(n_lists=32, pq_bits=8, pq_dim=8, seed=4), x)
    assert idx.rot_dim == 32 and idx.rot_dim % idx.pq_dim == 0
    # rotation rows orthonormal: R Rᵀ = I
    rrt = np.array(idx.rotation) @ np.array(idx.rotation).T
    np.testing.assert_allclose(rrt, np.eye(30), atol=1e-4)
    d, i = search(SearchParams(n_probes=24), idx, q, 10)
    _, ti = knn(x, q, 10, DistanceType.L2Expanded)
    # coarser gate: 8 codes over 30 rotated dims is a low-resolution config
    assert recall(i, np.array(ti)) >= 0.6


def test_ivf_pq_low_precision_lut():
    x, q = make_data(n=2500, dim=32)
    idx = build(IndexParams(n_lists=32, pq_bits=8, pq_dim=16, seed=6), x)
    d32, i32 = search(SearchParams(n_probes=16, lut_dtype="float32"),
                      idx, q, 10)
    dbf, ibf = search(SearchParams(n_probes=16, lut_dtype="bfloat16"),
                      idx, q, 10)
    _, ti = knn(x, q, 10, DistanceType.L2Expanded)
    r32 = recall(i32, np.array(ti))
    rbf = recall(ibf, np.array(ti))
    assert r32 >= 0.85
    # low-precision LUT degrades recall only slightly (reference doc note)
    assert rbf >= r32 - 0.1


def test_ivf_pq_inner_product():
    x, q = make_data(n=2500, dim=32, seed=9)
    idx = build(IndexParams(n_lists=32, pq_bits=8, pq_dim=16,
                            metric=DistanceType.InnerProduct, seed=7), x)
    d, i = search(SearchParams(n_probes=16), idx, q, 10)
    _, ti = knn(x, q, 10, DistanceType.InnerProduct)
    assert recall(i, np.array(ti)) >= 0.75
    # IP scores descend
    d = np.array(d)
    assert np.all(np.diff(d, axis=1) <= 1e-3)


def test_ivf_pq_approx_distance_quality():
    x, q = make_data(n=2000, dim=32)
    idx = build(IndexParams(n_lists=32, pq_bits=8, pq_dim=16, seed=8), x)
    d, i = search(SearchParams(n_probes=32), idx, q, 5)
    td, ti = knn(x, q, 5, DistanceType.L2Expanded)
    # PQ distances approximate true distances within the quantization error
    rel = np.abs(np.array(d) - np.array(td)) / np.maximum(np.array(td), 1.0)
    assert np.median(rel) < 0.25


# storage-size property; layout correctness rides pack_roundtrip +
# extend_packed_bits4 (tier-1 budget, PR 4)
@pytest.mark.slow
def test_ivf_pq_packed_storage_bytes():
    # pq_bits=4 codes cost half the bytes of pq_bits=8 (reference packing
    # contract ivf_pq_types.hpp:56-65): storage per slot is
    # ceil(pq_dim*pq_bits/8) bytes.
    x, _ = make_data()
    for bits, nbytes in [(4, 8), (5, 10), (6, 12), (8, 16)]:
        idx = build(IndexParams(n_lists=50, pq_bits=bits, pq_dim=16, seed=5), x)
        assert idx.list_codes.shape[2] == nbytes, (bits, idx.list_codes.shape)
        assert idx.list_codes.dtype == np.uint8


def test_ivf_pq_pack_roundtrip():
    from raft_tpu.neighbors.ivf_pq import _pack_codes, _unpack_codes
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    for pq_dim, bits in [(16, 4), (12, 5), (16, 6), (8, 7), (16, 8), (5, 4)]:
        codes = jnp.asarray(rng.integers(0, 1 << bits, (37, pq_dim)),
                            jnp.uint8)
        packed = _pack_codes(codes, bits)
        assert packed.shape == (37, -(-pq_dim * bits // 8))
        out = _unpack_codes(packed, pq_dim, bits)
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(codes, np.int32))


@pytest.mark.slow  # tier-1 budget (ISSUE-20 rebalance): pq extend is
# carried by test_ivf_pq_int_dtype_build_extend_search (same path, plus
# int dtypes) and the ivf_build tiled/chunked extend equivalences
def test_ivf_pq_extend():
    from raft_tpu.neighbors.ivf_pq import extend

    x, q = make_data(n=4000)
    k = 10
    n0 = 3600
    idx = build(IndexParams(n_lists=50, pq_bits=8, pq_dim=16, seed=5), x[:n0])
    idx = extend(idx, x[n0:], np.arange(n0, 4000, dtype=np.int32))
    assert idx.size == 4000
    d, i = search(SearchParams(n_probes=20), idx, q, k)
    _, ti = knn(x, q, k, DistanceType.L2Expanded)
    # recall with 10% appended matches the all-at-once build gate
    assert recall(i, np.array(ti)) >= 0.85
    # appended ids are findable: query the new vectors themselves
    d2, i2 = search(SearchParams(n_probes=20), idx, x[n0:n0 + 32], 1)
    hit = np.mean(np.asarray(i2)[:, 0] == np.arange(n0, n0 + 32))
    assert hit >= 0.9


def test_ivf_pq_extend_packed_bits4():
    from raft_tpu.neighbors.ivf_pq import extend

    x, q = make_data()
    idx = build(IndexParams(n_lists=50, pq_bits=4, pq_dim=16, seed=5),
                x[:3600])
    idx = extend(idx, x[3600:])
    assert idx.size == 4000 and idx.list_codes.shape[2] == 8
    d, i = search(SearchParams(n_probes=20), idx, q, 10)
    _, ti = knn(x, q, 10, DistanceType.L2Expanded)
    assert recall(i, np.array(ti)) >= 0.55


def test_ivf_pq_fp8_lut():
    x, q = make_data(n=2500, dim=32)
    idx = build(IndexParams(n_lists=32, pq_bits=8, pq_dim=16, seed=6), x)
    d32, i32 = search(SearchParams(n_probes=16, lut_dtype="float32"),
                      idx, q, 10)
    d8, i8 = search(SearchParams(n_probes=16, lut_dtype="float8_e4m3"),
                    idx, q, 10)
    _, ti = knn(x, q, 10, DistanceType.L2Expanded)
    r32 = recall(i32, np.array(ti))
    r8 = recall(i8, np.array(ti))
    assert r8 >= r32 - 0.15, (r8, r32)
    # dequantized distances stay close to the f32-LUT distances
    rel = (np.abs(np.array(d8) - np.array(d32))
           / np.maximum(np.array(d32), 1.0))
    assert np.median(rel) < 0.1


def test_ivf_pq_fp8_lut_adversarial_dynamic_range():
    """Adversarial numerics for the fp8 LUT (VERDICT r2 weak #8): feature
    subspaces spanning ≥1e4 in scale.  The per-query affine quantization
    (ivf_pq.py fp8 path; reference dequant ivf_pq_search.cuh:469-494) scales
    by the GLOBAL per-query LUT peak, so small-scale subspaces collapse to
    few fp8 levels — but their contribution to L2 ranking is proportionally
    small, so top-1 agreement with the f32 LUT must survive.

    Failure envelope (documented, not asserted): if ranking-RELEVANT
    distance differences live entirely in the small-scale subspaces (e.g.
    ties in every large-scale subspace), fp8's ~2^-4 relative resolution per
    (query, subspace) row can flip neighbors — per-subspace rescaling would
    be needed, at the cost of a non-rank-preserving LUT without a per-
    subspace dequant pass."""
    rng = np.random.default_rng(11)
    n, dim, nq = 3000, 32, 64
    ds = 4  # pq_dim=8 subspaces of 4 dims
    # per-subspace scales: 1e2 .. 1e-2 (spread 1e4)
    scales = np.repeat(np.logspace(2, -2, dim // ds), ds)
    x = (rng.normal(0, 1, (n, dim)) * scales).astype(np.float32)
    q = (rng.normal(0, 1, (nq, dim)) * scales).astype(np.float32)
    idx = build(IndexParams(n_lists=24, pq_bits=8, pq_dim=8, seed=7), x)
    d32, i32 = search(SearchParams(n_probes=12, lut_dtype="float32"),
                      idx, q, 10)
    d8, i8 = search(SearchParams(n_probes=12, lut_dtype="float8_e4m3"),
                    idx, q, 10)
    top1 = np.mean(np.asarray(i8)[:, 0] == np.asarray(i32)[:, 0])
    assert top1 >= 0.9, f"fp8 top-1 agreement vs f32 LUT {top1}"
    # top-10 set overlap stays high as well
    overlap = np.mean([len(set(a.tolist()) & set(b.tolist())) / 10.0
                       for a, b in zip(np.asarray(i8), np.asarray(i32))])
    assert overlap >= 0.8, f"fp8 top-10 overlap vs f32 LUT {overlap}"


def test_ivf_pq_search_uses_stream_pool():
    """Batched search records each in-flight batch on the caller handle's
    pool streams (VERDICT r2 weak #6: the pool must own real work — the
    reference overlaps batched kernels the same way, handle.hpp:88-130;
    here the overlap is XLA async dispatch across query batches)."""
    from raft_tpu.core import Handle

    x, q = make_data(n=1500, dim=32)
    idx = build(IndexParams(n_lists=16, pq_bits=8, pq_dim=8, seed=3), x)
    h = Handle(n_streams=2)
    nq = 64
    d, i = search(SearchParams(n_probes=8), idx, q[:nq], 5,
                  batch_size_query=16, handle=h)  # 4 batches over 2 streams
    pools = [h.get_stream_from_stream_pool(j) for j in range(2)]
    assert all(len(s._inflight) > 0 for s in pools), "pool streams idle"
    h.sync()  # caller-owned sync drains main + pool
    assert all(s.query() for s in pools)
    assert np.asarray(d).shape == (nq, 5)


def test_ivf_pq_serialize_roundtrip(tmp_path):
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors.serialize import load_ivf_pq, save_ivf_pq

    rng = np.random.default_rng(4)
    x = rng.normal(0, 1, (800, 32)).astype(np.float32)
    idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=8, pq_dim=8, pq_bits=4,
                                          seed=3), x)
    p = tmp_path / "pq.npz"
    save_ivf_pq(p, idx)
    idx2 = load_ivf_pq(p)
    assert idx2.pq_bits == 4 and idx2.codebook_kind == idx.codebook_kind
    sp = ivf_pq.SearchParams(n_probes=4)
    d1, i1 = ivf_pq.search(sp, idx, x[:16], 5)
    d2, i2 = ivf_pq.search(sp, idx2, x[:16], 5)
    np.testing.assert_array_equal(np.array(i1), np.array(i2))
    np.testing.assert_allclose(np.array(d1), np.array(d2), rtol=1e-6)
    # extend works on a loaded index
    idx3 = ivf_pq.extend(idx2, x[:50] + 0.01)
    assert idx3.size == idx.size + 50


def test_serialize_atomic_write_and_corruption_detection(tmp_path):
    """ISSUE 14 satellite (docs/serving.md §failure model): saves go via
    temp file + atomic rename (no droppings, overwrite-in-place safe) and
    the checksummed manifest turns ANY bit flip into a LOUD typed
    CorruptionError at load — never garbage results."""
    import os

    from raft_tpu.core.error import CorruptionError
    from raft_tpu.neighbors import ivf_flat, ivf_pq
    from raft_tpu.neighbors.serialize import (load_ivf_flat, load_ivf_pq,
                                              save_ivf_flat, save_ivf_pq)

    rng = np.random.default_rng(5)
    x = rng.normal(0, 1, (600, 16)).astype(np.float32)
    pq = ivf_pq.build(ivf_pq.IndexParams(n_lists=8, pq_dim=8, pq_bits=8,
                                         seed=3), x)
    flat = ivf_flat.build(ivf_flat.IndexParams(n_lists=8), x)
    p_pq = tmp_path / "pq.npz"
    p_flat = tmp_path / "flat.npz"
    save_ivf_pq(p_pq, pq)
    save_ivf_flat(p_flat, flat)
    # overwrite in place (the crash-mid-save scenario's steady state):
    # the rename is atomic, and no temp droppings survive
    save_ivf_pq(p_pq, pq)
    assert not [f for f in os.listdir(tmp_path) if ".tmp-" in f]
    load_ivf_pq(p_pq)
    load_ivf_flat(p_flat)

    # flip one byte mid-archive → loud typed error, for BOTH kinds
    for p, loader in ((p_pq, load_ivf_pq), (p_flat, load_ivf_flat)):
        blob = bytearray(p.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        p.write_bytes(bytes(blob))
        with pytest.raises(CorruptionError):
            loader(p)

    # truncation (crash mid-write without the atomic rename) is equally
    # typed — a half-written archive can never half-parse
    save_ivf_pq(p_pq, pq)
    blob = p_pq.read_bytes()
    p_pq.write_bytes(blob[:len(blob) // 2])
    with pytest.raises(CorruptionError):
        load_ivf_pq(p_pq)


def test_ivf_pq_adc_matches_reconstruction_oracle():
    """ADC scoring must be EXACT given the quantization: with all lists
    probed, search distances equal ||q − (center + decoded code)||² and the
    ranking equals the reconstruction-ranking oracle (proves the LUT
    pipeline adds no error beyond quantization itself).

    Information-limited recall bound (PR 3 triage): BECAUSE the pipeline
    is oracle-exact, recall on isotropic data is capped by what the codes
    can express, not by LUT precision — on N(0,1) 32-dim data at ds=4
    dims/subquantizer the ceiling is ~0.6 (TestAnnDispatch[ivf_pq]
    measures 0.53 at nprobe=8/32, 0.62 with ALL lists probed, identical
    across {hoisted, in-scan} pipelines and {f32, bf16} LUT dtypes with
    the build-time list tables exact in f32).  Correlated/clustered data
    escapes the bound (see rotation_kind="pca_balanced" and the bench.py
    ivf_pq data-model note)."""
    import jax.numpy as jnp

    from raft_tpu.cluster import min_cluster_and_distance
    from raft_tpu.neighbors import ivf_pq

    rng = np.random.default_rng(6)
    n, dim, nq, k = 3000, 32, 24, 5
    x = rng.normal(0, 1, (n, dim)).astype(np.float32)
    q = rng.normal(0, 1, (nq, dim)).astype(np.float32)
    index = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=8,
                                            seed=2), x)
    d, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), index, q, k)
    d, i = np.asarray(d), np.asarray(i)

    labels = np.asarray(min_cluster_and_distance(jnp.asarray(x),
                                                 index.centers).key)
    centers = np.asarray(index.centers)
    rot = np.asarray(index.rotation)
    cb = np.asarray(index.codebooks)                     # (pq_dim, 256, ds)
    pq_dim, _, ds = cb.shape
    sub = ((x - centers[labels]) @ rot).reshape(n, pq_dim, ds)
    codes = np.stack([((sub[:, m, None, :] - cb[m][None]) ** 2).sum(-1).argmin(1)
                      for m in range(pq_dim)], axis=1)
    recon_rot = (centers[labels] @ rot) + np.concatenate(
        [cb[m][codes[:, m]] for m in range(pq_dim)], axis=1)
    dd = (((q @ rot)[:, None, :] - recon_rot[None]) ** 2).sum(-1)
    oracle_i = np.argsort(dd, axis=1, kind="stable")[:, :k]
    oracle_d = np.take_along_axis(dd, oracle_i, axis=1)
    np.testing.assert_allclose(np.sort(d, axis=1), np.sort(oracle_d, axis=1),
                               rtol=2e-3, atol=2e-3)
    # rankings agree wherever distances aren't tied
    same = np.mean([len(set(a.tolist()) & set(b.tolist())) / k
                    for a, b in zip(i, oracle_i)])
    assert same > 0.99


@pytest.mark.slow  # trains two rotations on a correlated 10k set (budget)
def test_ivf_pq_pca_balanced_rotation():
    """OPQ-style eigenvalue-allocation rotation: orthogonal, recall at
    least as good as identity on correlated data, and serializes."""
    from raft_tpu.neighbors import ivf_pq, knn

    rng = np.random.default_rng(8)
    n, dim, nq, k, rank = 8000, 32, 64, 5, 8
    proj = rng.normal(0, 1, (rank, dim)) / np.sqrt(rank)
    x = (rng.normal(0, 1, (n, rank)) @ proj
         + rng.normal(0, 0.05, (n, dim))).astype(np.float32)
    q = x[:nq] + 0.02 * rng.normal(0, 1, (nq, dim)).astype(np.float32)
    _, ti = knn(x, q, k)
    ti = np.asarray(ti)

    def recall(kind):
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=16, pq_dim=8, pq_bits=8, seed=1, rotation_kind=kind), x)
        _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), idx, q, k)
        i = np.asarray(i)
        return idx, sum(len(set(a.tolist()) & set(b.tolist()))
                        for a, b in zip(i, ti)) / ti.size

    idx_pca, r_pca = recall("pca_balanced")
    _, r_def = recall("default")
    rot = np.asarray(idx_pca.rotation)
    np.testing.assert_allclose(rot @ rot.T, np.eye(dim), atol=1e-4)
    assert r_pca >= r_def - 0.02, (r_pca, r_def)


def test_ivf_pq_pca_rotation_requires_divisible_dim():
    from raft_tpu.core.error import RaftError
    from raft_tpu.neighbors import ivf_pq

    x = np.random.default_rng(0).normal(0, 1, (500, 30)).astype(np.float32)
    with pytest.raises(RaftError, match="pca_balanced"):
        ivf_pq.build(ivf_pq.IndexParams(n_lists=8, pq_dim=8,
                                        rotation_kind="pca_balanced"), x)


def test_ivf_pq_search_tail_bucketing_bounds_executables():
    """Varying query counts must not compile one executable per distinct
    ragged tail: tails are padded to the next power of two, results
    sliced (a serving-path compile-storm guard)."""
    from raft_tpu.neighbors.ivf_pq import _search_batch_aot

    x, q = make_data(n=1500, dim=32, n_queries=80)
    idx = build(IndexParams(n_lists=16, pq_bits=8, pq_dim=8, seed=3), x)
    ref_d, ref_i = search(SearchParams(n_probes=8), idx, q[:70], 5,
                          batch_size_query=64)
    n0 = _search_batch_aot.cache_size
    for nq in (69, 67, 66):  # tails 5, 3, 2 -> all bucket to 8
        d, i = search(SearchParams(n_probes=8), idx, q[:nq], 5,
                      batch_size_query=64)
        assert np.asarray(d).shape == (nq, 5)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ref_i)[:nq])
    assert _search_batch_aot.cache_size <= n0 + 1  # one bucketed tail exe


def test_ivf_pq_int_dtype_build_extend_search():
    """int8/uint8 datasets (reference T template, neighbors/ivf_pq.cuh:62):
    build tags the dtype, extend enforces it, search accepts the build
    dtype (or f32), and recall on integer data matches the f32 path's
    ballpark (the grid test owns the calibrated gates)."""
    from raft_tpu.core.error import LogicError
    from raft_tpu.neighbors import ivf_pq

    x, q = make_data(n=3000, dim=32)
    s = 127.0 / np.abs(x).max()
    xi = np.clip(np.round(x * s), -127, 127).astype(np.int8)
    qi = np.clip(np.round(q * s), -127, 127).astype(np.int8)

    idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=40, pq_dim=16, pq_bits=8,
                                          seed=5), xi)
    assert idx.dataset_dtype == "int8"
    # codes/codebooks stay dtype-independent
    assert idx.list_codes.dtype == np.uint8
    assert idx.codebooks.dtype == np.float32

    _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=20), idx, qi, 10)
    _, ti = knn(xi.astype(np.float32), qi.astype(np.float32), 10,
                DistanceType.L2Expanded)
    assert recall(i, np.array(ti)) >= 0.8

    # f32 queries are accepted against an int8-built index
    _, i32 = ivf_pq.search(ivf_pq.SearchParams(n_probes=20), idx,
                           qi.astype(np.float32), 10)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i32))

    # extend must match the build dtype
    idx2 = ivf_pq.extend(idx, xi[:64], np.arange(3000, 3064, dtype=np.int32))
    assert idx2.size == 3064 and idx2.dataset_dtype == "int8"
    with pytest.raises(LogicError):
        ivf_pq.extend(idx, xi[:8].astype(np.float32))
    with pytest.raises(LogicError):
        ivf_pq.extend(idx, xi[:8].astype(np.uint8))
    # uint8 queries on an int8 index are a dtype error too
    with pytest.raises(LogicError):
        ivf_pq.search(ivf_pq.SearchParams(n_probes=4), idx,
                      qi.astype(np.uint8), 10)
    # dtypes outside the reference's T set are rejected at build
    with pytest.raises(LogicError):
        ivf_pq.build(ivf_pq.IndexParams(n_lists=8), xi.astype(np.int32))


def test_ivf_pq_int_dtype_serialize_roundtrip(tmp_path):
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors.serialize import load_ivf_pq, save_ivf_pq

    rng = np.random.default_rng(9)
    xu = rng.integers(0, 256, (800, 32)).astype(np.uint8)
    idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=8, pq_dim=8, pq_bits=8,
                                          seed=3), xu)
    assert idx.dataset_dtype == "uint8"
    p = tmp_path / "pq_u8.npz"
    save_ivf_pq(p, idx)
    idx2 = load_ivf_pq(p)
    assert idx2.dataset_dtype == "uint8"
    sp = ivf_pq.SearchParams(n_probes=4)
    d1, i1 = ivf_pq.search(sp, idx, xu[:16], 5)
    d2, i2 = ivf_pq.search(sp, idx2, xu[:16], 5)
    np.testing.assert_array_equal(np.array(i1), np.array(i2))
    np.testing.assert_allclose(np.array(d1), np.array(d2), rtol=1e-6)


@pytest.mark.slow  # tier-1 budget (ISSUE-20 rebalance): bf16 storage
# rounding is carried by the flat bf16 recall test; pq recall by the f32
# recall grid
def test_ivf_pq_bf16_dataset_recall_within_pq_noise():
    """bf16 datasets build and search end-to-end; recall lands within PQ
    quantization noise of the f32 index (bf16 storage rounding ~8e-3 is
    far below the pq_dim=8-on-32-dims coding error)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    x = rng.random((5000, 32)).astype(np.float32)
    q = rng.random((50, 32)).astype(np.float32)
    _, iref = knn(x, q, 5)

    def recall(xx, qq):
        idx = build(IndexParams(n_lists=50, pq_dim=8, pq_bits=8, seed=1), xx)
        _, i = search(SearchParams(n_probes=10), idx, qq, 5)
        return np.mean([len(set(a.tolist()) & set(b.tolist())) / 5
                        for a, b in zip(np.asarray(i), np.asarray(iref))])

    rec_f32 = recall(x, q)
    rec_bf = recall(jnp.asarray(x, jnp.bfloat16), jnp.asarray(q, jnp.bfloat16))
    assert rec_bf >= rec_f32 - 0.05, (rec_bf, rec_f32)


# repeated-extend stress; single-extend exactness rides
# test_ivf_pq_extend + extend_packed_bits4 (tier-1 budget, PR 4)
@pytest.mark.slow
def test_ivf_pq_repeated_extend_exact_codes():
    """r5 incremental extend: repeated extends keep every stored code
    byte-identical to encoding the same row directly (the extend path must
    place codes, not recompute or disturb neighbours), and searching the
    extended index equals searching an index whose lists were packed from
    all rows at once with the same trained model."""
    from raft_tpu.neighbors import ivf_pq as m

    x, q = make_data(n=3000)
    idx = build(IndexParams(n_lists=40, pq_bits=8, pq_dim=16, seed=7),
                x[:2000])
    idx = m.extend(idx, x[2000:2400])
    idx = m.extend(idx, x[2400:3000],
                   np.arange(2400, 3000, dtype=np.int32))
    assert idx.size == 3000
    # physical accounting: live rows sum to size; dummy row empty
    assert int(np.asarray(idx.phys_sizes).sum()) == 3000
    assert int(np.asarray(idx.phys_sizes)[-1]) == 0
    assert (np.asarray(idx.list_indices)[-1] == -1).all()
    # every id present exactly once
    ids = np.asarray(idx.list_indices)
    ids = np.sort(ids[ids >= 0])
    np.testing.assert_array_equal(ids, np.arange(3000))
    # searching the new rows finds them (ADC self-match)
    _, i2 = search(SearchParams(n_probes=40), idx, x[2400:2432], 1)
    hit = np.mean(np.asarray(i2)[:, 0] == np.arange(2400, 2432))
    assert hit >= 0.9


# serialize x extend cross; both axes covered solo by
# serialize_roundtrip + extend (tier-1 budget, PR 4)
@pytest.mark.slow
def test_ivf_pq_serialize_roundtrip_after_extend(tmp_path):
    """save → load → search equality must hold for an INCREMENTALLY
    extended index (r5: extend leaves non-contiguous per-list chunk
    layouts that serialization must capture exactly)."""
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors.serialize import load_ivf_pq, save_ivf_pq

    x, q = make_data(n=2500)
    idx = build(IndexParams(n_lists=30, pq_bits=8, pq_dim=16, seed=9),
                x[:2000])
    idx = ivf_pq.extend(idx, x[2000:])
    p = str(tmp_path / "pq_ext.idx")
    save_ivf_pq(p, idx)
    idx2 = load_ivf_pq(p)
    d1, i1 = search(SearchParams(n_probes=15), idx, q, 10)
    d2, i2 = search(SearchParams(n_probes=15), idx2, q, 10)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)


@pytest.mark.slow  # all-lists probe-order sweep/stress (tier-1 budget, PR 4)
def test_ivf_pq_full_probe_order_invariance():
    """With ONE trained model (add_data_on_build=False — reference
    ann::index_params knob, r5 parity addition), full-probe search results
    must be identical whether the rows arrived in one extend or three:
    chunk layout is an implementation detail the scores may not leak."""
    from raft_tpu.neighbors import ivf_pq

    x, q = make_data(n=2000)
    params = IndexParams(n_lists=20, pq_bits=8, pq_dim=16, seed=4,
                         add_data_on_build=False)
    trained = build(params, x)
    assert trained.size == 0
    one = ivf_pq.extend(trained, x)
    three = ivf_pq.extend(trained, x[:800])
    three = ivf_pq.extend(three, x[800:1500],
                          np.arange(800, 1500, dtype=np.int32))
    three = ivf_pq.extend(three, x[1500:],
                          np.arange(1500, 2000, dtype=np.int32))
    sp = SearchParams(n_probes=20)
    d1, i1 = search(sp, one, q, 10)
    d3, i3 = search(sp, three, q, 10)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i3))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d3), rtol=1e-5,
                               atol=1e-5)
