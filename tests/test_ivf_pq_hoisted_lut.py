"""Hoisted-ADC LUT pipeline (docs/ivf_pq_adc.md): hoisted ≡ in-scan
property grid, the single-per-query fp8 affine contract, serialize v2
round-trip + v1 compat, the trace-time LUT counters, and the ci/lint.py
probe-scan regression guard."""

import json

import numpy as np
import pytest

from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors import ivf_pq
from raft_tpu.neighbors.ivf_pq import (
    CodebookKind,
    IndexParams,
    SearchParams,
    build,
    search,
)

L2 = DistanceType.L2Expanded
L2S = DistanceType.L2SqrtExpanded
IP = DistanceType.InnerProduct


def make_data(n=2000, dim=32, n_queries=48, seed=0, clusters=20):
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 5, (clusters, dim))
    x = (centers[rng.integers(0, clusters, n)]
         + rng.normal(0, 1, (n, dim))).astype(np.float32)
    q = (centers[rng.integers(0, clusters, n_queries)]
         + rng.normal(0, 1, (n_queries, dim))).astype(np.float32)
    return x, q


def overlap(a, b):
    a, b = np.asarray(a), np.asarray(b)
    k = a.shape[1]
    return np.mean([len(set(r.tolist()) & set(s.tolist())) / k
                    for r, s in zip(a, b)])


_BUILDS = {}


def built(kind, metric, bits):
    """One build per (codebook kind, metric, pq_bits) — shared across the
    lut_dtype axis of the grid, both A/B sides must score the same index."""
    key = (kind, metric, bits)
    if key not in _BUILDS:
        x, q = make_data()
        idx = build(IndexParams(n_lists=16, pq_dim=8, pq_bits=bits,
                                codebook_kind=kind, metric=metric, seed=3), x)
        _BUILDS[key] = (idx, q)
    return _BUILDS[key]


# {PER_SUBSPACE, PER_CLUSTER} × {L2, L2Sqrt, IP} × pq_bits {4, 5, 8}: the
# metric axis rides PER_SUBSPACE, the bits axis rides L2, PER_CLUSTER
# covers both score forms (L2 + IP) — 7 builds instead of 18, every axis
# still exercised against every pipeline stage.
CONFIGS = [
    (CodebookKind.PER_SUBSPACE, L2, 8),
    (CodebookKind.PER_SUBSPACE, L2S, 8),
    (CodebookKind.PER_SUBSPACE, IP, 8),
    (CodebookKind.PER_SUBSPACE, L2, 4),
    (CodebookKind.PER_SUBSPACE, L2, 5),
    (CodebookKind.PER_CLUSTER, L2, 8),
    (CodebookKind.PER_CLUSTER, IP, 8),
]
_IDS = [f"{k.name}-{m.name}-b{b}" for k, m, b in CONFIGS]

# The f32 ids-equal grid is the strong gate and runs fully in tier-1; the
# compressed-dtype grids (bf16/fp8) are noise-bounded OVERLAP tests whose
# per-config information largely repeats — tier-1 keeps one representative
# config per codebook kind and the full cross re-runs under -m slow
# (tier-1 budget, PR 4).
_BF16_KEEP = {(CodebookKind.PER_SUBSPACE, L2, 8),
              (CodebookKind.PER_CLUSTER, L2, 8)}
_FP8_KEEP = {(CodebookKind.PER_SUBSPACE, L2, 8),
             (CodebookKind.PER_CLUSTER, IP, 8)}


def _curated(keep):
    return [pytest.param(*c, id=i) if c in keep
            else pytest.param(*c, id=i, marks=pytest.mark.slow)
            for c, i in zip(CONFIGS, _IDS)]


@pytest.mark.parametrize("kind,metric,bits", CONFIGS, ids=_IDS)
def test_hoisted_matches_inscan_f32(kind, metric, bits):
    """f32 LUT: same top-k IDS as the in-scan path (the bench acceptance
    gate) and distances equal to accumulation-order tolerance — the two
    pipelines sum the identical ADC decomposition in different
    association orders, so bit-identity is not on the table but ranking
    identity is."""
    idx, q = built(kind, metric, bits)
    dh, ih = search(SearchParams(n_probes=8, hoisted_lut=True), idx, q, 10)
    dl, il = search(SearchParams(n_probes=8, hoisted_lut=False), idx, q, 10)
    np.testing.assert_array_equal(np.asarray(ih), np.asarray(il))
    np.testing.assert_allclose(np.asarray(dh), np.asarray(dl),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("kind,metric,bits", _curated(_BF16_KEEP))
def test_hoisted_matches_inscan_bf16(kind, metric, bits):
    """bf16 LUT: the hoisted path quantizes the COMBINED list+query cross
    terms and keeps ‖r‖² in the exact f32 base, the legacy path rounds the
    full LUT — equal only to bf16 noise, bounded as top-k overlap."""
    idx, q = built(kind, metric, bits)
    sp = dict(n_probes=8, lut_dtype="bfloat16")
    _, ih = search(SearchParams(**sp, hoisted_lut=True), idx, q, 10)
    _, il = search(SearchParams(**sp, hoisted_lut=False), idx, q, 10)
    assert overlap(ih, il) >= 0.8, overlap(ih, il)


@pytest.mark.parametrize("kind,metric,bits", _curated(_FP8_KEEP))
def test_hoisted_fp8_vs_f32_topk(kind, metric, bits):
    """fp8 regression (the latent-affine-bug satellite): hoisted fp8 top-k
    must overlap the f32 top-k — one per-(query, probe-set) affine keeps
    candidates from different probe tiles mutually comparable."""
    idx, q = built(kind, metric, bits)
    _, i32 = search(SearchParams(n_probes=8, hoisted_lut=True), idx, q, 10)
    _, i8 = search(SearchParams(n_probes=8, lut_dtype="float8_e4m3",
                                hoisted_lut=True), idx, q, 10)
    assert overlap(i8, i32) >= 0.7, overlap(i8, i32)
    # and against the legacy fp8 path (same decomposition, per-tile affine)
    _, l8 = search(SearchParams(n_probes=8, lut_dtype="float8_e4m3",
                                hoisted_lut=False), idx, q, 10)
    assert overlap(i8, l8) >= 0.7, overlap(i8, l8)


def test_fp8_single_affine_per_query():
    """The fp8 contract itself: ONE scale per query over the whole probe
    set (shape (nq,)), shifts re-entering exactly through the f32 base."""
    import jax.numpy as jnp

    from raft_tpu.neighbors.ivf_pq import _quantize_lut

    rng = np.random.default_rng(5)
    nq, P, pq_dim, kcb = 6, 4, 8, 16
    lut = jnp.asarray(rng.normal(0, 3, (nq, P, pq_dim, kcb)), jnp.float32)
    base = jnp.asarray(rng.normal(0, 1, (nq, P)), jnp.float32)
    lut_q, base2, scale = _quantize_lut(lut, base, "float8_e4m3")
    assert scale.shape == (nq,)
    assert lut_q.dtype == jnp.float8_e4m3fn
    # dequantized lookup + shifted base reproduces the f32 sum to fp8 noise
    codes = rng.integers(0, kcb, (nq, P, pq_dim))
    take = np.take_along_axis(np.asarray(lut, np.float32).reshape(
        nq, P, pq_dim, kcb), codes[..., None], axis=-1)[..., 0].sum(-1)
    want = take + np.asarray(base)
    got = (np.asarray(lut_q, np.float32).reshape(nq, P, pq_dim, kcb)[
        np.arange(nq)[:, None, None], np.arange(P)[None, :, None],
        np.arange(pq_dim)[None, None, :], codes].sum(-1)
        / np.asarray(scale)[:, None] + np.asarray(base2))
    span = np.abs(want).max()
    np.testing.assert_allclose(got, want, atol=0.15 * span)
    # f32 passthrough keeps base/scale inert
    lut_f, base_f, scale_f = _quantize_lut(lut, base, "float32")
    np.testing.assert_array_equal(np.asarray(base_f), np.asarray(base))
    np.testing.assert_array_equal(np.asarray(scale_f), np.ones(nq))


def test_trace_time_lut_counters():
    """The ``Comms.collective_calls``-style assertion: tracing the hoisted
    search program bumps the per-batch counter and NOT the in-scan one —
    a hoisted trace bumping ``in_scan_lut_builds`` would mean codebook
    einsums crept back into the probe-scan body."""
    x, q = make_data(n=1100, dim=32, n_queries=21, seed=7)
    idx = build(IndexParams(n_lists=12, pq_dim=8, pq_bits=8, seed=9), x)
    c = ivf_pq.lut_trace_counters
    before = dict(c)
    search(SearchParams(n_probes=6, hoisted_lut=True), idx, q, 9)
    assert c["in_scan_lut_builds"] == before.get("in_scan_lut_builds", 0)
    assert c["hoisted_lut_builds"] > before.get("hoisted_lut_builds", 0)
    mid = dict(c)
    search(SearchParams(n_probes=6, hoisted_lut=False), idx, q, 9)
    assert c["in_scan_lut_builds"] > mid.get("in_scan_lut_builds", 0)
    assert c["hoisted_lut_builds"] == mid.get("hoisted_lut_builds", 0)


def test_env_gate_and_param_override(monkeypatch):
    from raft_tpu.neighbors.ivf_pq import hoisted_lut_enabled

    monkeypatch.delenv("RAFT_TPU_HOISTED_LUT", raising=False)
    assert hoisted_lut_enabled()
    monkeypatch.setenv("RAFT_TPU_HOISTED_LUT", "0")
    assert not hoisted_lut_enabled()
    # explicit SearchParams.hoisted_lut overrides the env gate: with the
    # env forcing legacy, hoisted=True must still trace the hoisted program
    x, q = make_data(n=900, dim=32, n_queries=17, seed=11)
    idx = build(IndexParams(n_lists=10, pq_dim=8, pq_bits=8, seed=1), x)
    c = ivf_pq.lut_trace_counters
    before = dict(c)
    search(SearchParams(n_probes=5, hoisted_lut=True), idx, q, 7)
    assert c["in_scan_lut_builds"] == before.get("in_scan_lut_builds", 0)


def test_index_carries_adc_tables():
    """Build populates the stage-1 tables with the documented shapes and
    exact-f32 values; extend carries list_adc over and keeps list_csum
    consistent with a from-scratch recompute of the packed codes."""
    from raft_tpu.neighbors.ivf_pq import _csum_for_packed

    x, _ = make_data(n=1500)
    idx = build(IndexParams(n_lists=16, pq_dim=8, pq_bits=8, seed=3), x)
    assert idx.list_adc.shape == (16, 8, 256)
    assert idx.list_adc.dtype == np.float32
    assert idx.list_csum.shape == idx.list_indices.shape
    idx2 = ivf_pq.extend(idx, x[:100] + 0.01)
    np.testing.assert_array_equal(np.asarray(idx2.list_adc),
                                  np.asarray(idx.list_adc))
    want = np.asarray(_csum_for_packed(
        idx2.list_codes, idx2.owner, idx2.centers, idx2.rotation,
        idx2.codebooks, False, 8))
    got = np.asarray(idx2.list_csum)
    live = np.asarray(idx2.list_indices) >= 0
    np.testing.assert_allclose(got[live], want[live], rtol=1e-5, atol=1e-5)


def test_serialize_v2_roundtrip_new_fields(tmp_path):
    from raft_tpu.neighbors.serialize import load_ivf_pq, save_ivf_pq

    x, q = make_data(n=1200)
    idx = build(IndexParams(n_lists=12, pq_dim=8, pq_bits=5, seed=2), x)
    p = tmp_path / "pq_v2.npz"
    save_ivf_pq(p, idx)
    with np.load(p) as z:
        header = json.loads(bytes(z["__header__"]).decode())
        assert header["version"] == 2
        assert "list_adc" in z.files and "list_csum" in z.files
    idx2 = load_ivf_pq(p)
    np.testing.assert_array_equal(np.asarray(idx2.list_adc),
                                  np.asarray(idx.list_adc))
    np.testing.assert_array_equal(np.asarray(idx2.list_csum),
                                  np.asarray(idx.list_csum))
    sp = SearchParams(n_probes=6)
    d1, i1 = search(sp, idx, q, 8)
    d2, i2 = search(sp, idx2, q, 8)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)


def _downgrade_to_v1(path):
    """Rewrite a v2 archive as the pre-hoist v1 format: strip the ADC
    tables, stamp version 1 (what an old writer would have produced)."""
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files}
    header = json.loads(bytes(arrays.pop("__header__")).decode())
    header["version"] = 1
    # a real pre-hoist writer also predates the checksum manifest
    header.pop("checksums", None)
    for k in ("list_adc", "list_csum"):
        arrays.pop(k)
    arrays["__header__"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8)
    np.savez(path, **arrays)


@pytest.mark.parametrize("kind", [CodebookKind.PER_SUBSPACE,
                                  CodebookKind.PER_CLUSTER],
                         ids=["per_subspace", "per_cluster"])
def test_load_v1_archive_recomputes_tables(tmp_path, kind):
    """Old-format load: a v1 archive (no list_adc/list_csum) loads and the
    recomputed tables reproduce the original index's searches exactly —
    the tables are pure functions of the trained model + stored codes."""
    from raft_tpu.neighbors.serialize import load_ivf_pq, save_ivf_pq

    x, q = make_data(n=1200)
    idx = build(IndexParams(n_lists=12, pq_dim=8, pq_bits=8,
                            codebook_kind=kind, seed=4), x)
    p = str(tmp_path / "pq_v1.npz")
    save_ivf_pq(p, idx)
    _downgrade_to_v1(p)
    idx2 = load_ivf_pq(p)
    np.testing.assert_allclose(np.asarray(idx2.list_adc),
                               np.asarray(idx.list_adc), rtol=1e-6)
    live = np.asarray(idx.list_indices) >= 0
    np.testing.assert_allclose(np.asarray(idx2.list_csum)[live],
                               np.asarray(idx.list_csum)[live],
                               rtol=1e-5, atol=1e-5)
    sp = SearchParams(n_probes=6)
    d1, i1 = search(sp, idx, q, 8)
    d2, i2 = search(sp, idx2, q, 8)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5)


def test_unreadable_version_rejected(tmp_path):
    from raft_tpu.core.error import RaftError
    from raft_tpu.neighbors.serialize import load_ivf_pq, save_ivf_pq

    x, _ = make_data(n=600)
    idx = build(IndexParams(n_lists=8, pq_dim=8, pq_bits=8, seed=4), x)
    p = str(tmp_path / "pq_v99.npz")
    save_ivf_pq(p, idx)
    with np.load(p) as z:
        arrays = {k: z[k] for k in z.files}
    header = json.loads(bytes(arrays.pop("__header__")).decode())
    header["version"] = 99
    arrays["__header__"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8)
    np.savez(p, **arrays)
    with pytest.raises(RaftError, match="version"):
        load_ivf_pq(p)


class TestProbeScanLintRule:
    """ci/lint.py's hoisted-ADC regression guard: einsum/take_along_axis
    over closed-over operands inside a scan_probe_lists tile callback."""

    _VIOLATION = '''
import jax.numpy as jnp
def search(codebooks, rot_q, probes, idxs, sizes):
    def score_tile(rows):
        lut = jnp.einsum("qmd,mkd->qmk", rot_q, codebooks)
        return lut.sum(-1)
    return scan_probe_lists(probes, score_tile, idxs, sizes, 5, True, None)
'''

    def _check(self, src):
        import ast

        from ci.lint import check_probe_scan_callbacks

        return check_probe_scan_callbacks(ast.parse(src), src.splitlines())

    def test_flags_closed_over_einsum(self):
        f = self._check(self._VIOLATION)
        assert len(f) == 1 and "einsum" in f[0][1]

    def test_marker_allowlists(self):
        src = self._VIOLATION.replace(
            "rot_q, codebooks)", "rot_q, codebooks)  # adc-exempt")
        assert self._check(src) == []

    def test_local_operands_pass(self):
        src = self._VIOLATION.replace("rot_q, codebooks", "rows, rows")
        assert self._check(src) == []

    def test_alias_does_not_launder_closure(self):
        """A local alias of a closed-over operand (``cb = codebooks``) is
        still closed-over data — taint tracking keeps the rule firing on
        the exact legacy per-tile-LUT shape it exists to catch."""
        src = self._VIOLATION.replace(
            '        lut = jnp.einsum("qmd,mkd->qmk", rot_q, codebooks)',
            "        cb = codebooks\n"
            '        lut = jnp.einsum("qmd,mkd->qmk", rot_q, cb)')
        f = self._check(src)
        assert len(f) == 1 and "einsum" in f[0][1]

    def test_nested_scope_name_collision_still_flags(self):
        """Scope resolution is per function: a nested helper whose params
        shadow the closed-over operands must not launder the closure at
        the callsite's scope (the flat any-binding-anywhere heuristic's
        false negative)."""
        src = self._VIOLATION.replace(
            "    def score_tile(rows):",
            "    def score_tile(rows):\n"
            "        def helper(rot_q, codebooks):\n"
            "            return rot_q\n")
        f = self._check(src)
        assert len(f) == 1 and "einsum" in f[0][1]

    def test_nested_helper_params_are_local_in_helper(self):
        """Inside the nested helper itself, its params ARE local — the
        sanctioned _lookup pattern (tile + LUT arrive as arguments)."""
        src = self._VIOLATION.replace(
            'lut = jnp.einsum("qmd,mkd->qmk", rot_q, codebooks)',
            "def lookup(tile, lut_t):\n"
            '            return jnp.einsum("qk,qk->q", tile, lut_t)\n'
            "        lut = lookup(rows, rows)")
        assert self._check(src) == []

    def test_scoped_to_neighbors(self, tmp_path):
        from ci.lint import check_file

        d = tmp_path / "raft_tpu" / "neighbors"
        d.mkdir(parents=True)
        f = d / "mod.py"
        f.write_text(self._VIOLATION)
        assert any("scan_probe_lists" in msg for _, msg in check_file(f))
        other = tmp_path / "raft_tpu" / "cluster"
        other.mkdir()
        g = other / "mod.py"
        g.write_text(self._VIOLATION)
        assert not any("scan_probe_lists" in m for _, m in check_file(g))

    def test_shipped_neighbors_tree_clean(self):
        import pathlib

        from ci.lint import check_file

        root = pathlib.Path(__file__).resolve().parents[1]
        for f in sorted((root / "raft_tpu" / "neighbors").glob("*.py")):
            assert not check_file(f), f
