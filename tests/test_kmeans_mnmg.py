"""MNMG k-means tests — BASELINE config[4] path (distributed EM over a mesh),
validated against the single-device implementation."""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import cluster
from raft_tpu.cluster import KMeansParams, InitMethod
from raft_tpu.cluster import kmeans_mnmg
from raft_tpu.comms import build_comms
from raft_tpu.random import RngState, make_blobs
from raft_tpu.stats import adjusted_rand_index


@pytest.fixture(scope="module")
def comms():
    return build_comms()


@pytest.fixture
def blobs():
    x, labels, centers = make_blobs(RngState(11), 1600, 12, n_clusters=4,
                                    cluster_std=0.4)
    return np.asarray(x), np.asarray(labels), np.asarray(centers)


def test_distributed_matches_single_device(comms, blobs):
    x, true_labels, centers = blobs
    params = KMeansParams(n_clusters=4, init=InitMethod.Array, max_iter=50)
    out_single = cluster.fit(params, x, centroids=centers)
    out_dist = kmeans_mnmg.fit(params, comms, x, centroids=centers)
    # identical init + deterministic EM → identical result up to fp reduction order
    np.testing.assert_allclose(np.asarray(out_dist.centroids),
                               np.asarray(out_single.centroids), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(float(out_dist.inertia), float(out_single.inertia),
                               rtol=1e-4)


def test_distributed_ari(comms, blobs):
    x, true_labels, _ = blobs
    params = KMeansParams(n_clusters=4, max_iter=100, seed=0)
    out = kmeans_mnmg.fit(params, comms, x)
    labels, inertia = kmeans_mnmg.predict(params, comms, x, out.centroids)
    ari = float(adjusted_rand_index(np.asarray(labels), true_labels))
    assert ari > 0.99, f"ARI {ari}"
    assert float(inertia) > 0


def test_host_loop_matches_device_loop(comms, blobs):
    """loop="host" (reference raft-dask shape: host-driven per-iteration
    step + allreduce) reaches the same fit as the single-program
    while_loop path."""
    x, _, centers = blobs
    params = KMeansParams(n_clusters=4, init=InitMethod.Array, max_iter=50)
    out_dev = kmeans_mnmg.fit(params, comms, x, centroids=centers)
    out_host = kmeans_mnmg.fit(params, comms, x, centroids=centers,
                               loop="host")
    np.testing.assert_allclose(np.asarray(out_host.centroids),
                               np.asarray(out_dev.centroids), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(float(out_host.inertia),
                               float(out_dev.inertia), rtol=1e-4)
    # host loop checks convergence every sync_every iters, so it may run
    # up to sync_every-1 extra EM steps past the device loop's stop point
    assert int(out_dev.n_iter) <= int(out_host.n_iter) \
        <= int(out_dev.n_iter) + 7


def test_fori_loop_matches_device_loop(comms, blobs):
    """loop="fori" (static-trip fori_loop with masked post-convergence
    updates — the r5 while_loop A/B candidate) is SEMANTICALLY IDENTICAL
    to the while_loop path: same centroids, same inertia, same recorded
    n_iter stopping point."""
    x, _, centers = blobs
    params = KMeansParams(n_clusters=4, init=InitMethod.Array, max_iter=50,
                          tol=1e-4)
    out_dev = kmeans_mnmg.fit(params, comms, x, centroids=centers)
    out_fori = kmeans_mnmg.fit(params, comms, x, centroids=centers,
                               loop="fori")
    np.testing.assert_allclose(np.asarray(out_fori.centroids),
                               np.asarray(out_dev.centroids), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(float(out_fori.inertia),
                               float(out_dev.inertia), rtol=1e-5)
    assert int(out_fori.n_iter) == int(out_dev.n_iter)
    assert int(out_fori.n_iter) < 50  # converged before the static bound


def test_host_loop_tol_zero_runs_max_iter(comms, blobs):
    """tol=0 → no convergence sync points: exactly max_iter iterations
    (the fully-pipelined mode the MNMG bench exercises)."""
    x, _, centers = blobs
    params = KMeansParams(n_clusters=4, init=InitMethod.Array, max_iter=7,
                          tol=0.0)
    out = kmeans_mnmg.fit(params, comms, x, centroids=centers, loop="host")
    assert int(out.n_iter) == 7


def test_host_loop_rejects_unknown_mode(comms, blobs):
    from raft_tpu.core import LogicError

    x, _, centers = blobs
    with pytest.raises(LogicError):
        kmeans_mnmg.fit(KMeansParams(n_clusters=4), comms, x,
                        centroids=centers, loop="pipelined")
    with pytest.raises(LogicError):
        kmeans_mnmg.fit(KMeansParams(n_clusters=4), comms, x,
                        centroids=centers, loop="host", sync_every=0)


def test_compute_new_centroids_building_block(comms, blobs):
    """The pylibraft compute_new_centroids equivalent: one E+M step."""
    x, _, centers = blobs

    def fn(x_shard, c):
        new, wsum, inertia = kmeans_mnmg.compute_new_centroids(x_shard, c, comms)
        return new, wsum, inertia

    from jax.sharding import PartitionSpec as P
    import jax

    x_sharded = jax.device_put(
        jnp.asarray(x),
        jax.sharding.NamedSharding(comms.mesh, P(comms.axis_name, None)))
    new, wsum, inertia = comms.run(
        fn, x_sharded, jnp.asarray(centers),
        in_specs=(P(comms.axis_name, None), P(None, None)),
        out_specs=(P(None, None), P(None), P()),
    )
    # oracle: single-device one EM step
    nn = cluster.min_cluster_and_distance(jnp.asarray(x), jnp.asarray(centers))
    expected, wsum_exp = cluster.update_centroids(x, nn.key, 4,
                                                  old_centroids=jnp.asarray(centers))
    np.testing.assert_allclose(np.asarray(new), np.asarray(expected), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(wsum), np.asarray(wsum_exp), rtol=1e-6)
    np.testing.assert_allclose(float(inertia), float(cluster.cluster_cost(nn)),
                               rtol=1e-4)


def test_fused_step_single_allreduce(comms, blobs):
    """PR 2 wire-format guarantee: the fused EM iteration issues exactly
    ONE allreduce (the packed (k·d + k + 1) carry) where the pre-PR
    two-pass step issued three (sums / counts / inertia) — pinned via the
    comms trace-time collective-call counter."""
    from jax.sharding import PartitionSpec as P
    import jax

    x, _, centers = blobs
    xs = jax.device_put(
        jnp.asarray(x),
        jax.sharding.NamedSharding(comms.mesh, P(comms.axis_name, None)))
    c0 = jnp.asarray(centers)
    for fused, expected in ((True, 1), (False, 3)):
        before = comms.collective_calls["allreduce"]

        def step(xx, cc, _f=fused):  # fresh closure → fresh trace
            return kmeans_mnmg.compute_new_centroids(xx, cc, comms,
                                                     fused=_f)

        comms.run(step, xs, c0,
                  in_specs=(P(comms.axis_name, None), P(None, None)),
                  out_specs=(P(None, None), P(None), P()))
        assert comms.collective_calls["allreduce"] - before == expected


def test_fused_fit_matches_unfused(comms, blobs):
    """Full distributed fit: fused (single-pass EM + packed allreduce) ==
    unfused (two-pass + three collectives) on every loop form."""
    x, _, centers = blobs
    params = KMeansParams(n_clusters=4, init=InitMethod.Array, max_iter=50,
                          tol=1e-4)
    for loop in ("device", "fori"):
        a = kmeans_mnmg.fit(params, comms, x, centroids=centers, loop=loop,
                            fused=True)
        b = kmeans_mnmg.fit(params, comms, x, centroids=centers, loop=loop,
                            fused=False)
        assert int(a.n_iter) == int(b.n_iter)
        np.testing.assert_allclose(np.asarray(a.centroids),
                                   np.asarray(b.centroids), rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(float(a.inertia), float(b.inertia),
                                   rtol=1e-4)


def test_uneven_shards_rejected(comms):
    from raft_tpu.core import LogicError

    x = np.random.default_rng(0).random((1001, 4)).astype(np.float32)
    with pytest.raises(LogicError):
        kmeans_mnmg.fit(KMeansParams(n_clusters=2), comms, x)


def test_knn_mnmg_matches_single_device(comms):
    """OPG sharded brute-force kNN == single-device kNN (up to ties)."""
    from raft_tpu.neighbors import knn
    from raft_tpu.neighbors.knn_mnmg import knn_mnmg

    rng = np.random.default_rng(0)
    n = 64 * comms.get_size()
    x = rng.normal(0, 1, (n, 12)).astype(np.float32)
    q = rng.normal(0, 1, (24, 12)).astype(np.float32)
    d, i = knn_mnmg(comms, x, q, 5)
    dref, iref = knn(x, q, 5)
    np.testing.assert_allclose(np.asarray(d), np.asarray(dref), atol=1e-4)
    # distance sets agree even where exact ties permute ids
    assert np.mean(np.asarray(i) == np.asarray(iref)) > 0.99


def test_knn_mnmg_inner_product(comms):
    from raft_tpu.distance import DistanceType
    from raft_tpu.neighbors import knn
    from raft_tpu.neighbors.knn_mnmg import knn_mnmg

    rng = np.random.default_rng(1)
    n = 32 * comms.get_size()
    x = rng.normal(0, 1, (n, 8)).astype(np.float32)
    q = rng.normal(0, 1, (16, 8)).astype(np.float32)
    d, i = knn_mnmg(comms, x, q, 4, metric=DistanceType.InnerProduct)
    dref, iref = knn(x, q, 4, DistanceType.InnerProduct)
    np.testing.assert_allclose(np.asarray(d), np.asarray(dref), atol=1e-4)


def test_knn_mnmg_k_exceeds_shard_rejected(comms):
    from raft_tpu.core.error import RaftError
    from raft_tpu.neighbors.knn_mnmg import knn_mnmg

    rng = np.random.default_rng(2)
    n = 8 * comms.get_size()
    x = rng.normal(0, 1, (n, 4)).astype(np.float32)
    with pytest.raises(RaftError, match="rows per shard"):
        knn_mnmg(comms, x, x[:4], 9)


def test_fori_loop_bf16_matches_device_bf16(comms, blobs):
    """loop="fori" keeps the half-precision contract: bf16 data, f32 delta
    accumulation, identical stopping point vs the while path."""
    x, _, centers = blobs
    params = KMeansParams(n_clusters=4, init=InitMethod.Array, max_iter=40,
                          tol=1e-3)
    xb = jnp.asarray(x, jnp.bfloat16)
    cb = jnp.asarray(centers, jnp.bfloat16)
    out_dev = kmeans_mnmg.fit(params, comms, xb, centroids=cb)
    out_fori = kmeans_mnmg.fit(params, comms, xb, centroids=cb, loop="fori")
    assert out_fori.centroids.dtype == jnp.bfloat16
    assert out_fori.inertia.dtype == jnp.float32
    assert int(out_fori.n_iter) == int(out_dev.n_iter)
    np.testing.assert_allclose(
        np.asarray(out_fori.centroids, np.float32),
        np.asarray(out_dev.centroids, np.float32), rtol=1e-2, atol=1e-2)


def test_fori_tol_zero_matches_device_semantics(comms, blobs):
    """tol=0 means `delta > 0`: both single-program loops stop at an exact
    fixed point (unlike loop="host", which never syncs at tol=0 and runs
    max_iter — test_host_loop_tol_zero_runs_max_iter)."""
    x, _, centers = blobs
    params = KMeansParams(n_clusters=4, init=InitMethod.Array, max_iter=7,
                          tol=0.0)
    out_dev = kmeans_mnmg.fit(params, comms, x, centroids=centers)
    out = kmeans_mnmg.fit(params, comms, x, centroids=centers, loop="fori")
    assert int(out.n_iter) == int(out_dev.n_iter) <= 7


def test_predict_matches_fit_labels_across_loops(comms, blobs):
    """predict() on the fitted centroids yields identical labels whichever
    loop produced them, and inertia equals the fit's trailing E-step."""
    x, _, centers = blobs
    params = KMeansParams(n_clusters=4, init=InitMethod.Array, max_iter=30)
    outs = {m: kmeans_mnmg.fit(params, comms, x, centroids=centers, loop=m)
            for m in ("device", "fori", "host")}
    ref_labels = None
    for mode, out in outs.items():
        labels, inertia = kmeans_mnmg.predict(params, comms, x,
                                              out.centroids)
        assert labels.shape == (x.shape[0],)
        np.testing.assert_allclose(float(inertia), float(out.inertia),
                                   rtol=1e-4)
        if ref_labels is None:
            ref_labels = np.asarray(labels)
        else:
            # same blobs, same init: all three loops converge to the same
            # partition
            from raft_tpu.stats import adjusted_rand_index as ari
            assert float(ari(jnp.asarray(ref_labels), labels)) == 1.0


def test_compute_new_centroids_weighted(comms, blobs):
    """sample_weights reweight the M-step mean (pylibraft
    compute_new_centroids signature parity): doubling a shard-constant
    weight must leave centroids unchanged, and weighting one cluster's
    rows pulls its centroid toward the weighted mean."""
    from jax.sharding import PartitionSpec as P

    x, _, centers = blobs
    n = x.shape[0]
    xs = comms.globalize(jnp.asarray(x), P(comms.axis_name, None))
    c0 = jnp.asarray(centers)

    def step(xx, cc, w_mode):
        if w_mode == "uniform2":
            w = 2.0 * jnp.ones(xx.shape[0], xx.dtype)
        else:
            w = jnp.ones(xx.shape[0], xx.dtype)
        new, wsum, _ = kmeans_mnmg.compute_new_centroids(
            xx, cc, comms, sample_weights=w)
        return new, wsum

    unw = comms.run(lambda xx, cc: step(xx, cc, "ones"), xs, c0,
                    in_specs=(P(comms.axis_name, None), P(None, None)),
                    out_specs=(P(None, None), P()))
    dbl = comms.run(lambda xx, cc: step(xx, cc, "uniform2"), xs, c0,
                    in_specs=(P(comms.axis_name, None), P(None, None)),
                    out_specs=(P(None, None), P()))
    np.testing.assert_allclose(np.asarray(unw[0]), np.asarray(dbl[0]),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dbl[1]),
                               2.0 * np.asarray(unw[1]), rtol=1e-6)
    assert float(jnp.sum(unw[1])) == pytest.approx(n)
