"""Fused-scan kNN pipeline properties (the PR-1 tentpole): sorted-run
merge oracle tests, hoisted-stats scan vs the full-matrix reference path
(bit-identical on tie-free data), query-batch padding, and int64-safe
global id offsets.

Reference analogue: cpp/test/neighbors/knn.cu + fused_l2_knn.cu check the
fused kernel against the materialized-matrix path the same way.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.distance import DistanceType, pairwise_distance
from raft_tpu.matrix import merge_sorted_runs, select_k
from raft_tpu.neighbors import knn


def _merge_oracle(a_vals, a_idx, b_vals, b_idx, k, select_min):
    """Host oracle: stable merge preferring run a on ties."""
    out_v, out_i = [], []
    for av, ai, bv, bi in zip(a_vals, a_idx, b_vals, b_idx):
        cat_v = np.concatenate([av, bv])
        cat_i = np.concatenate([ai, bi])
        order = np.argsort(cat_v if select_min else -cat_v, kind="stable")
        out_v.append(cat_v[order][:k])
        out_i.append(cat_i[order][:k])
    return np.stack(out_v), np.stack(out_i)


class TestMergeSortedRuns:
    @pytest.mark.parametrize("ka,kb,k", [(5, 5, 5), (7, 3, 7), (3, 8, 6),
                                         (1, 1, 1), (4, 4, 8)])
    @pytest.mark.parametrize("select_min", [True, False])
    def test_vs_stable_merge_oracle(self, ka, kb, k, select_min):
        rng = np.random.default_rng(ka * 100 + kb * 10 + k)
        a = np.sort(rng.random((6, ka)).astype(np.float32), axis=1)
        b = np.sort(rng.random((6, kb)).astype(np.float32), axis=1)
        if not select_min:
            a, b = -a, -b
        ai = rng.integers(0, 1000, (6, ka)).astype(np.int32)
        bi = rng.integers(0, 1000, (6, kb)).astype(np.int32)
        v, i = merge_sorted_runs(a, ai, b, bi, k=k, select_min=select_min)
        rv, ri = _merge_oracle(a, ai, b, bi, k, select_min)
        n_real = min(k, ka + kb)
        np.testing.assert_array_equal(np.asarray(v)[:, :n_real],
                                      rv[:, :n_real])
        np.testing.assert_array_equal(np.asarray(i)[:, :n_real],
                                      ri[:, :n_real])
        # slots past the union get sentinel / -1 (the empty-slot convention)
        if n_real < k:
            pad_v = np.asarray(v)[:, n_real:]
            assert np.all(np.isinf(pad_v))
            assert np.all((pad_v > 0) == select_min)
            assert np.all(np.asarray(i)[:, n_real:] == -1)

    def test_ties_prefer_run_a(self):
        """Run a's elements win ties — the property that makes the scan's
        running merge reproduce a stable full sort (earlier tiles = lower
        ids = run a)."""
        a = np.array([[1.0, 2.0, 3.0]], np.float32)
        b = np.array([[1.0, 2.0, 3.0]], np.float32)
        ai = np.array([[10, 11, 12]], np.int32)
        bi = np.array([[20, 21, 22]], np.int32)
        v, i = merge_sorted_runs(a, ai, b, bi, k=4)
        np.testing.assert_array_equal(np.asarray(v), [[1.0, 1.0, 2.0, 2.0]])
        np.testing.assert_array_equal(np.asarray(i), [[10, 20, 11, 21]])

    def test_nan_orders_worst_and_drops_nothing(self):
        """NaN candidates sort after every real value (±inf included) and
        never displace finite candidates — plain comparisons are all-false
        around NaN, which would collide merged ranks and silently drop
        real neighbors (a shard containing one NaN row must not eat a
        real result in knn_merge_parts)."""
        a = np.array([[0.1, 0.5, np.nan]], np.float32)
        b = np.array([[0.2, 0.3, 0.4]], np.float32)
        ai = np.array([[10, 11, 12]], np.int32)
        bi = np.array([[20, 21, 22]], np.int32)
        v, i = merge_sorted_runs(a, ai, b, bi, k=3)
        np.testing.assert_array_equal(np.asarray(i), [[10, 20, 21]])
        np.testing.assert_allclose(np.asarray(v), [[0.1, 0.2, 0.3]])
        # among NaNs: run a first; after every finite/inf value
        a2 = np.array([[1.0, np.inf, np.nan]], np.float32)
        b2 = np.array([[2.0, np.nan, np.nan]], np.float32)
        v, i = merge_sorted_runs(a2, np.array([[0, 1, 2]], np.int32),
                                 b2, np.array([[5, 6, 7]], np.int32), k=6)
        np.testing.assert_array_equal(np.asarray(i), [[0, 5, 1, 2, 6, 7]])

    @pytest.mark.parametrize("select_min", [True, False])
    def test_wide_k_concat_branch_matches_rank_path(self, select_min,
                                                    monkeypatch):
        """Past _MERGE_CONCAT_MIN_K the merge switches from the O(k²)
        rank arithmetic to one stable top-k over the concatenation
        (ISSUE 18: refine-ratio candidate runs are merged at k·ratio).
        Both paths must agree with the host oracle — including NaN tails
        surviving as NaN values."""
        import importlib

        # raft_tpu.matrix re-exports the select_k FUNCTION over the module
        sk_mod = importlib.import_module("raft_tpu.matrix.select_k")

        k = 40
        rng = np.random.default_rng(7)
        a = np.sort(rng.random((5, k)).astype(np.float32), axis=1)
        b = np.sort(rng.random((5, k)).astype(np.float32), axis=1)
        if not select_min:
            a, b = -a, -b
        a[0, -2:] = np.nan                      # NaN tail stays a valid run
        ai = rng.integers(0, 10_000, (5, k)).astype(np.int32)
        bi = rng.integers(0, 10_000, (5, k)).astype(np.int32)
        assert k >= sk_mod._MERGE_CONCAT_MIN_K  # the branch actually runs
        wv, wi = merge_sorted_runs(a, ai, b, bi, k=k, select_min=select_min)
        monkeypatch.setattr(sk_mod, "_MERGE_CONCAT_MIN_K", 10**9)
        sk_mod._merge_aot._cache.clear()        # force a rank-path retrace
        rv, ri = merge_sorted_runs(a, ai, b, bi, k=k, select_min=select_min)
        sk_mod._merge_aot._cache.clear()        # don't leak the patched
        np.testing.assert_array_equal(np.asarray(wi), np.asarray(ri))
        np.testing.assert_array_equal(np.asarray(wv), np.asarray(rv))
        ov, oi = _merge_oracle(np.where(np.isnan(a), np.inf if select_min
                                        else -np.inf, a), ai, b, bi, k,
                               select_min)
        np.testing.assert_array_equal(np.asarray(wi), oi)

    def test_matches_select_k_over_concat(self):
        """merge(sorted runs) ≡ select_k(concat) on tie-free data — the
        exact substitution the scan makes."""
        rng = np.random.default_rng(3)
        a = np.sort(rng.random((9, 6)).astype(np.float32), axis=1)
        b = np.sort(rng.random((9, 6)).astype(np.float32), axis=1)
        ai = np.arange(54, dtype=np.int32).reshape(9, 6)
        bi = (100 + np.arange(54, dtype=np.int32)).reshape(9, 6)
        mv, mi = merge_sorted_runs(a, ai, b, bi, k=6)
        sv, si = select_k(np.concatenate([a, b], axis=1), 6,
                          indices=np.concatenate([ai, bi], axis=1))
        np.testing.assert_array_equal(np.asarray(mv), np.asarray(sv))
        np.testing.assert_array_equal(np.asarray(mi), np.asarray(si))


_METRICS = [
    pytest.param(DistanceType.L2SqrtExpanded, id="l2sqrt"),
    pytest.param(DistanceType.CosineExpanded, id="cosine"),
    pytest.param(DistanceType.InnerProduct, id="inner_product"),
    pytest.param(DistanceType.L1, id="l1"),
]


class TestFusedScanVsFullMatrix:
    """The acceptance property: the fused scan (hoisted stats + partial
    top-k + sorted-run merge, multiple tiles AND padded query batches) is
    bit-identical to the full-matrix pairwise_distance + select_k path on
    tie-free data — both pipelines run the same per-element epilogue, so
    even the distances must agree exactly, not just to tolerance."""

    def _data(self, dtype, seed=0, n=300, nq=45, dim=16):
        rng = np.random.default_rng(seed)
        x = jnp.asarray(rng.random((n, dim)), dtype)
        q = jnp.asarray(rng.random((nq, dim)), dtype)
        return x, q

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("metric", _METRICS)
    def test_bit_identical(self, metric, dtype):
        k = 10
        x, q = self._data(dtype)
        select_min = metric != DistanceType.InnerProduct
        # full-matrix reference: one pairwise call + one stable select
        full = pairwise_distance(q, x, metric)
        rd, ri = select_k(full, k, select_min=select_min)
        rd, ri = np.asarray(rd), np.asarray(ri)
        # tie-free precondition (guaranteed for continuous data at these
        # seeds; assert so a silent tie can never weaken the test)
        assert all(len(np.unique(row[np.isfinite(row)])) == k
                   for row in rd), "test data must be tie-free"
        # fused scan, forced through multiple index tiles and a ragged
        # (padded) query batch
        d, i = knn(x, q, k, metric, batch_size_index=64,
                   batch_size_query=32)
        np.testing.assert_array_equal(np.asarray(i), ri)
        np.testing.assert_array_equal(np.asarray(d), rd)

    @pytest.mark.parametrize("metric", _METRICS)
    def test_tiling_invariant(self, metric):
        """Any (batch_size_index, batch_size_query) pair produces the
        same results as the single-tile scan."""
        k = 7
        x, q = self._data(jnp.float32, seed=1)
        select_min = metric != DistanceType.InnerProduct
        d0, i0 = knn(x, q, k, metric)
        for bi, bq in [(64, 45), (100, 7), (300, 16)]:
            d, i = knn(x, q, k, metric, batch_size_index=bi,
                       batch_size_query=bq)
            np.testing.assert_array_equal(np.asarray(i), np.asarray(i0)), \
                (bi, bq, select_min)
            np.testing.assert_array_equal(np.asarray(d), np.asarray(d0))


class TestQueryBatchPadding:
    def test_ragged_tail_shares_bucket_executable(self):
        """Remainder batches pad to the bucketed shape: two different
        remainders in the same bucket must NOT trace a second scan
        executable (the recompile-per-residue cost the padding removes)."""
        from raft_tpu.neighbors.brute_force import _knn_scan, _knn_scan_aot

        def cache_size():
            # eager numpy inputs dispatch the AOT cache; the jit cache
            # covers traced/off-device callers — count both so the
            # no-recompile property holds regardless of route
            return _knn_scan._cache_size() + _knn_scan_aot.cache_size

        rng = np.random.default_rng(2)
        x = rng.random((100, 8)).astype(np.float32)
        base = cache_size()
        knn(x, rng.random((33, 8)).astype(np.float32), 3,
            batch_size_query=32)  # full batch (32) + remainder 1 → pad 8
        grew = cache_size() - base
        assert grew >= 1
        knn(x, rng.random((36, 8)).astype(np.float32), 3,
            batch_size_query=32)  # remainder 4 → same bucket of 8
        assert cache_size() - base == grew

    def test_padded_tail_results_match_unbatched(self):
        rng = np.random.default_rng(4)
        x = rng.random((120, 8)).astype(np.float32)
        q = rng.random((33, 8)).astype(np.float32)
        d1, i1 = knn(x, q, 5)
        d2, i2 = knn(x, q, 5, batch_size_query=32)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))


class TestGlobalIdOffset:
    def test_small_offset_stays_int32(self):
        rng = np.random.default_rng(5)
        x = rng.random((50, 4)).astype(np.float32)
        q = rng.random((6, 4)).astype(np.float32)
        d0, i0 = knn(x, q, 3)
        d, i = knn(x, q, 3, global_id_offset=1000)
        assert np.asarray(i).dtype == np.int32
        np.testing.assert_array_equal(np.asarray(i), np.asarray(i0) + 1000)

    def test_offset_past_int32_requires_x64(self):
        """Ids past 2^31 must fail loudly (or go int64 under x64), never
        silently wrap — the knn_mnmg sharded-id hazard."""
        from raft_tpu.core.error import RaftError

        rng = np.random.default_rng(6)
        x = rng.random((20, 4)).astype(np.float32)
        q = rng.random((3, 4)).astype(np.float32)
        if jax.config.jax_enable_x64:
            _, i = knn(x, q, 2, global_id_offset=2**31)
            assert np.asarray(i).dtype == np.int64
            assert np.asarray(i).min() >= 2**31
        else:
            with pytest.raises(RaftError, match="int32"):
                knn(x, q, 2, global_id_offset=2**31)

    def test_negative_offset_rejected(self):
        from raft_tpu.core.error import RaftError

        rng = np.random.default_rng(7)
        x = rng.random((10, 4)).astype(np.float32)
        with pytest.raises(RaftError, match=">= 0"):
            knn(x, x[:2], 2, global_id_offset=-5)
