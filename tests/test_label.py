"""Label utility tests — counterpart of reference cpp/test/label/*."""

import numpy as np


from raft_tpu import label
from raft_tpu.matrix import select_k


def test_unique_labels():
    labels = np.array([5, 2, 5, 9, 2])
    np.testing.assert_array_equal(label.get_unique_labels(labels), [2, 5, 9])


def test_ovr():
    labels = np.array([0, 1, 2, 1])
    np.testing.assert_array_equal(label.get_ovr_labels(labels, 1), [0, 1, 0, 1])


def test_make_monotonic():
    labels = np.array([10, 30, 10, 20, 30])
    np.testing.assert_array_equal(label.make_monotonic(labels), [0, 2, 0, 1, 2])
    np.testing.assert_array_equal(
        label.make_monotonic(labels, zero_based=False), [1, 3, 1, 2, 3]
    )


def test_merge_labels():
    # two chains merged through the mask: {0,1} via a, {1,2} via b
    labels_a = np.array([0, 0, 2, 3], np.int32)
    labels_b = np.array([1, 2, 2, 3], np.int32)
    mask = np.array([False, True, True, False])
    out = np.asarray(label.merge_labels(labels_a, labels_b, mask))
    # nodes 0,1 share class a=0; nodes 1,2 share class b=2 → {0,1,2} get 0
    np.testing.assert_array_equal(out, [0, 0, 0, 3])


def test_select_k():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 100)).astype(np.float32)
    vals, idx = select_k(x, 5, select_min=True)
    expected = np.sort(x, axis=1)[:, :5]
    np.testing.assert_allclose(np.asarray(vals), expected, rtol=1e-6)
    np.testing.assert_allclose(
        np.take_along_axis(x, np.asarray(idx), axis=1), expected, rtol=1e-6
    )
    vals_max, _ = select_k(x, 3, select_min=False)
    np.testing.assert_allclose(np.asarray(vals_max), -np.sort(-x, axis=1)[:, :3],
                               rtol=1e-6)


def test_select_k_payload():
    x = np.array([[3.0, 1.0, 2.0]])
    payload = np.array([[30, 10, 20]])
    vals, idx = select_k(x, 2, indices=payload)
    np.testing.assert_array_equal(np.asarray(idx), [[10, 20]])
