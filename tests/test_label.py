"""Label utility tests — counterpart of reference cpp/test/label/*
(label.cu, merge_labels.cu), with a union-find oracle grid replacing the
reference's handful of fixed cases."""

import numpy as np
import pytest


from raft_tpu import label
from raft_tpu.matrix import select_k


def _merge_labels_oracle(labels_a, labels_b, mask):
    """Pure-python union-find oracle for merge_labels' contract: nodes
    sharing a labels_a class are connected; masked nodes sharing a
    labels_b class are additionally connected; every node receives the
    minimum labels_a value of its merged component."""
    n = len(labels_a)
    parent = list(range(n))

    def find(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    def union(i, j):
        ri, rj = find(i), find(j)
        if ri != rj:
            parent[rj] = ri

    first_of_a, first_of_b = {}, {}
    for i in range(n):
        union(i, first_of_a.setdefault(labels_a[i], i))
        if mask[i]:
            union(i, first_of_b.setdefault(labels_b[i], i))
    comp_min = {}
    for i in range(n):
        r = find(i)
        comp_min[r] = min(comp_min.get(r, labels_a[i]), labels_a[i])
    return np.array([comp_min[find(i)] for i in range(n)], np.int32)


def test_unique_labels():
    labels = np.array([5, 2, 5, 9, 2])
    np.testing.assert_array_equal(label.get_unique_labels(labels), [2, 5, 9])


def test_ovr():
    labels = np.array([0, 1, 2, 1])
    np.testing.assert_array_equal(label.get_ovr_labels(labels, 1), [0, 1, 0, 1])


def test_make_monotonic():
    labels = np.array([10, 30, 10, 20, 30])
    np.testing.assert_array_equal(label.make_monotonic(labels), [0, 2, 0, 1, 2])
    np.testing.assert_array_equal(
        label.make_monotonic(labels, zero_based=False), [1, 3, 1, 2, 3]
    )


def test_merge_labels():
    # two chains merged through the mask: {0,1} via a, {1,2} via b
    labels_a = np.array([0, 0, 2, 3], np.int32)
    labels_b = np.array([1, 2, 2, 3], np.int32)
    mask = np.array([False, True, True, False])
    out = np.asarray(label.merge_labels(labels_a, labels_b, mask))
    # nodes 0,1 share class a=0; nodes 1,2 share class b=2 → {0,1,2} get 0
    np.testing.assert_array_equal(out, [0, 0, 0, 3])


@pytest.mark.parametrize("n,n_classes,mask_frac,seed", [
    (10, 3, 0.5, 0),
    (100, 8, 0.3, 1),
    (100, 8, 0.9, 2),
    pytest.param(1000, 40, 0.5, 3, marks=pytest.mark.slow),  # budget
    (1000, 5, 0.2, 4),    # few big classes: long merge chains
    (257, 257, 0.5, 5),   # singleton classes: only the mask connects
])
def test_merge_labels_vs_union_find(n, n_classes, mask_frac, seed):
    """Random grid against the union-find oracle — the reference's
    merge_labels.cu fixed cases generalized."""
    rng = np.random.default_rng(seed)
    labels_a = rng.integers(0, n_classes, n).astype(np.int32)
    labels_b = rng.integers(0, n_classes, n).astype(np.int32)
    mask = rng.random(n) < mask_frac
    out = np.asarray(label.merge_labels(labels_a, labels_b, mask))
    np.testing.assert_array_equal(out,
                                  _merge_labels_oracle(labels_a, labels_b,
                                                       mask))


def test_merge_labels_mask_all_false_is_identity():
    """No masked nodes → labels_b never connects anything → labels_a
    classes keep their own (already-minimal) label values."""
    rng = np.random.default_rng(6)
    labels_a = rng.integers(0, 7, 50).astype(np.int32)
    out = np.asarray(label.merge_labels(labels_a,
                                        rng.integers(0, 7, 50).astype(np.int32),
                                        np.zeros(50, bool)))
    np.testing.assert_array_equal(out, labels_a)


def test_merge_labels_full_chain_collapses():
    """All-true mask + labels_b chaining every adjacent labels_a class →
    one component labeled with the global minimum."""
    # a classes: 0,1,2,3; b connects (0,1),(1,2),(2,3) — b values are node
    # ids in [0, n), per the r5-enforced precondition
    labels_a = np.array([0, 0, 1, 1, 2, 2, 3, 3], np.int32)
    labels_b = np.array([7, 4, 4, 5, 5, 6, 6, 7], np.int32)
    out = np.asarray(label.merge_labels(labels_a, labels_b,
                                        np.ones(8, bool)))
    # b=7 ALSO connects nodes 0 and 7 — still one component, min=0
    np.testing.assert_array_equal(out, np.zeros(8, np.int32))


def test_get_unique_labels_unsorted_negative():
    labels = np.array([3, -1, 7, -1, 3, 0])
    np.testing.assert_array_equal(label.get_unique_labels(labels),
                                  [-1, 0, 3, 7])


def test_ovr_custom_values():
    labels = np.array([0, 1, 2, 1])
    np.testing.assert_array_equal(
        np.asarray(label.get_ovr_labels(labels, 1, true_val=5, false_val=-5)),
        [-5, 5, -5, 5])


def test_make_monotonic_explicit_uniques_jit_safe():
    """With unique_labels given, the mapping is jit-traceable (static
    output shape — the reference's device-side variant)."""
    import jax

    labels = np.array([10, 30, 10, 20, 30])
    uniq = np.array([10, 20, 30])
    out = jax.jit(lambda l: label.make_monotonic(l, unique_labels=uniq))(labels)
    np.testing.assert_array_equal(np.asarray(out), [0, 2, 0, 1, 2])


def test_make_monotonic_native_matches_jnp():
    """The native C++ host fast path and the jnp searchsorted path agree
    (native path auto-selected for numpy input when built)."""
    rng = np.random.default_rng(7)
    labels = rng.choice([5, -3, 99, 12, 0], size=500).astype(np.int64)
    via_default = np.asarray(label.make_monotonic(labels))
    via_jnp = np.asarray(label.make_monotonic(
        labels, unique_labels=sorted(set(labels.tolist()))))
    np.testing.assert_array_equal(via_default, via_jnp)


def test_select_k():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 100)).astype(np.float32)
    vals, idx = select_k(x, 5, select_min=True)
    expected = np.sort(x, axis=1)[:, :5]
    np.testing.assert_allclose(np.asarray(vals), expected, rtol=1e-6)
    np.testing.assert_allclose(
        np.take_along_axis(x, np.asarray(idx), axis=1), expected, rtol=1e-6
    )
    vals_max, _ = select_k(x, 3, select_min=False)
    np.testing.assert_allclose(np.asarray(vals_max), -np.sort(-x, axis=1)[:, :3],
                               rtol=1e-6)


def test_select_k_payload():
    x = np.array([[3.0, 1.0, 2.0]])
    payload = np.array([[30, 10, 20]])
    vals, idx = select_k(x, 2, indices=payload)
    np.testing.assert_array_equal(np.asarray(idx), [[10, 20]])


# ---- r5 depth: sklearn/behavioral oracles for the classlabels family ----


def test_ovr_matches_sklearn_label_binarizer():
    """One-vs-rest columns match sklearn's LabelBinarizer for every class
    (reference label.cu's getOvrLabels cases, generalized)."""
    from sklearn.preprocessing import LabelBinarizer

    rng = np.random.default_rng(0)
    labels = rng.choice([2, 7, 11, 30], 200).astype(np.int32)
    lb = LabelBinarizer()
    ref = lb.fit_transform(labels)          # (n, n_classes), column order =
    for col, cls in enumerate(lb.classes_):  # sorted classes
        got = np.asarray(label.get_ovr_labels(labels, int(cls)))
        np.testing.assert_array_equal(got, ref[:, col])


def test_make_monotonic_matches_sklearn_label_encoder():
    from sklearn.preprocessing import LabelEncoder

    rng = np.random.default_rng(1)
    labels = rng.choice([-5, 0, 3, 1000, 2**20], 300).astype(np.int32)
    got = np.asarray(label.make_monotonic(labels))
    ref = LabelEncoder().fit_transform(labels)
    np.testing.assert_array_equal(got, ref)


def test_make_monotonic_one_based():
    labels = np.array([9, 3, 9, 7], np.int32)
    got = np.asarray(label.make_monotonic(labels, zero_based=False))
    np.testing.assert_array_equal(got, [3, 1, 3, 2])


def test_make_monotonic_single_class_and_singleton():
    np.testing.assert_array_equal(
        np.asarray(label.make_monotonic(np.full(7, 42, np.int32))),
        np.zeros(7))
    np.testing.assert_array_equal(
        np.asarray(label.make_monotonic(np.array([-3], np.int32))), [0])


def test_merge_labels_chain_vs_star_topology():
    """Two adversarial propagation shapes at the same component count: a
    chain a0-a1-...  linked pairwise through labels_b (forces the longest
    propagation distance) and a star (everything linked through one hub).
    Both must collapse to the minimum label of the whole component.
    Labels are node ids in [0, n) — the documented precondition."""
    n = 64
    # chain: a-labels pair consecutive nodes (i//2 pairs), b-labels pair
    # with offset 1 — union of both = one long path
    la = (np.arange(n) // 2).astype(np.int32) * 2 + 1   # in-range, sparse
    lb_ = ((np.arange(n) + 1) // 2).astype(np.int32)
    mask = np.ones(n, bool)
    got = np.asarray(label.merge_labels(la, lb_, mask))
    np.testing.assert_array_equal(got, np.full(n, 1))
    # star: all b-labels equal → one component through the hub
    la2 = np.arange(n).astype(np.int32)
    got2 = np.asarray(label.merge_labels(la2, np.zeros(n, np.int32), mask))
    np.testing.assert_array_equal(got2, np.zeros(n))
    # cross-check both shapes against the union-find oracle
    np.testing.assert_array_equal(got, _merge_labels_oracle(la, lb_, mask))
    np.testing.assert_array_equal(
        got2, _merge_labels_oracle(la2, np.zeros(n, np.int32), mask))


def test_merge_labels_respects_mask_boundaries():
    """Unmasked nodes keep their own a-component even when their b-label
    would bridge two components (the mask is the reference's core
    semantics, merge_labels.cuh)."""
    la = np.array([0, 0, 1, 1, 2, 2], np.int32)
    lb_ = np.array([4, 4, 4, 5, 5, 5], np.int32)
    mask = np.array([True, True, False, False, True, True])
    got = np.asarray(label.merge_labels(la, lb_, mask))
    # b connects {0,1} (class 4) and {4,5} (class 5); nodes 2,3 unmasked →
    # component {0,1} stays 0, {2,3} stays 1, {4,5} stays 2
    np.testing.assert_array_equal(got, [0, 0, 1, 1, 2, 2])
    oracle = _merge_labels_oracle(la, lb_, mask)
    np.testing.assert_array_equal(got, oracle)


def test_merge_labels_rejects_out_of_range_node_ids():
    """r5 finding: out-of-range labels used to be silently CLIPPED into a
    shared bucket, merging unrelated classes.  Concrete inputs now raise."""
    from raft_tpu.core import LogicError

    la = np.array([0, 0, 1, 1, 2, 2], np.int32)
    mask = np.ones(6, bool)
    with pytest.raises(LogicError, match="labels_b"):
        label.merge_labels(la, np.array([7, 7, 7, 8, 8, 8], np.int32), mask)
    with pytest.raises(LogicError, match="labels_a"):
        label.merge_labels(la * 3 + 5, la, mask)
    # out-of-range b at UNMASKED positions is fine (never read)
    lb_ = np.array([0, 0, 99, 99, 1, 1], np.int32)
    m2 = np.array([True, True, False, False, True, True])
    out = np.asarray(label.merge_labels(la, lb_, m2))
    np.testing.assert_array_equal(out, _merge_labels_oracle(la, lb_, m2))
