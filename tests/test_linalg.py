"""Dense linalg tests — counterpart of reference cpp/test/linalg/* (naive
host oracles via numpy, reference SURVEY.md §4)."""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import linalg
from raft_tpu.linalg import Apply, NormType


@pytest.fixture
def rng():
    return np.random.default_rng(42)


class TestElementwise:
    def test_binary(self, rng):
        x = rng.random((8, 16)).astype(np.float32)
        y = rng.random((8, 16)).astype(np.float32) + 0.5
        np.testing.assert_allclose(linalg.add(x, y), x + y, rtol=1e-6)
        np.testing.assert_allclose(linalg.subtract(x, y), x - y, rtol=1e-6)
        np.testing.assert_allclose(linalg.multiply(x, y), x * y, rtol=1e-6)
        np.testing.assert_allclose(linalg.divide(x, y), x / y, rtol=1e-5)
        np.testing.assert_allclose(linalg.power(jnp.abs(jnp.asarray(x)), 2.0), x**2, rtol=1e-5)
        np.testing.assert_allclose(linalg.sqrt(x), np.sqrt(x), rtol=1e-6)

    def test_scalar(self, rng):
        x = rng.random(32).astype(np.float32)
        np.testing.assert_allclose(linalg.add_scalar(x, 2.0), x + 2, rtol=1e-6)
        np.testing.assert_allclose(linalg.multiply_scalar(x, 3.0), x * 3, rtol=1e-6)

    def test_ops(self, rng):
        x = rng.random(16).astype(np.float32)
        y = rng.random(16).astype(np.float32)
        z = rng.random(16).astype(np.float32)
        np.testing.assert_allclose(linalg.unary_op(x, lambda a: a * 2), x * 2, rtol=1e-6)
        np.testing.assert_allclose(
            linalg.ternary_op(x, y, z, lambda a, b, c: a + b * c), x + y * z, rtol=1e-6
        )

    def test_map_offset(self):
        out = linalg.map_offset((2, 3), lambda i: i * 2)
        np.testing.assert_array_equal(out, [[0, 2, 4], [6, 8, 10]])


class TestReduce:
    def test_reduce_rows_cols(self, rng):
        x = rng.random((6, 10)).astype(np.float32)
        np.testing.assert_allclose(
            linalg.reduce(x, Apply.ALONG_COLUMNS), x.sum(axis=1), rtol=1e-5
        )
        np.testing.assert_allclose(
            linalg.reduce(x, Apply.ALONG_ROWS), x.sum(axis=0), rtol=1e-5
        )

    def test_reduce_ops(self, rng):
        x = rng.standard_normal((6, 10)).astype(np.float32)
        # sum of squares with final sqrt = L2 row norm
        out = linalg.reduce(x, Apply.ALONG_COLUMNS, main_op=lambda v: v * v,
                            final_op=jnp.sqrt)
        np.testing.assert_allclose(out, np.linalg.norm(x, axis=1), rtol=1e-5)
        out = linalg.reduce(x, Apply.ALONG_COLUMNS, init=np.inf,
                            reduce_op=jnp.minimum)
        np.testing.assert_allclose(out, x.min(axis=1), rtol=1e-6)

    def test_norms(self, rng):
        x = rng.standard_normal((5, 7)).astype(np.float32)
        np.testing.assert_allclose(
            linalg.row_norm(x, NormType.L1Norm), np.abs(x).sum(axis=1), rtol=1e-5
        )
        # RAFT L2 "norm" is the squared norm
        np.testing.assert_allclose(
            linalg.row_norm(x, NormType.L2Norm), (x * x).sum(axis=1), rtol=1e-5
        )
        np.testing.assert_allclose(
            linalg.col_norm(x, NormType.LinfNorm), np.abs(x).max(axis=0), rtol=1e-6
        )

    def test_map_then_reduce(self, rng):
        x = rng.random((4, 4)).astype(np.float32)
        out = linalg.map_then_reduce(lambda a: a * a, x)
        np.testing.assert_allclose(out, (x * x).sum(), rtol=1e-5)

    def test_mse(self, rng):
        a = rng.random(100).astype(np.float32)
        b = rng.random(100).astype(np.float32)
        np.testing.assert_allclose(
            linalg.mean_squared_error(a, b), ((a - b) ** 2).mean(), rtol=1e-5
        )

    def test_reduce_rows_by_key(self, rng):
        x = rng.random((10, 4)).astype(np.float32)
        keys = np.array([0, 1, 0, 2, 1, 0, 2, 2, 1, 0])
        out = linalg.reduce_rows_by_key(x, keys, 3)
        expected = np.stack([x[keys == k].sum(axis=0) for k in range(3)])
        np.testing.assert_allclose(out, expected, rtol=1e-5)
        # weighted
        w = rng.random(10).astype(np.float32)
        out = linalg.reduce_rows_by_key(x, keys, 3, weights=w)
        expected = np.stack([(x[keys == k] * w[keys == k, None]).sum(axis=0) for k in range(3)])
        np.testing.assert_allclose(out, expected, rtol=1e-5)

    def test_reduce_cols_by_key(self, rng):
        x = rng.random((4, 6)).astype(np.float32)
        keys = np.array([0, 1, 1, 2, 0, 2])
        out = linalg.reduce_cols_by_key(x, keys, 3)
        expected = np.stack([x[:, keys == k].sum(axis=1) for k in range(3)], axis=1)
        np.testing.assert_allclose(out, expected, rtol=1e-5)

    def test_normalize(self, rng):
        x = rng.standard_normal((5, 8)).astype(np.float32)
        out = np.asarray(linalg.normalize(x))
        np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, rtol=1e-5)


class TestBlas:
    def test_gemm(self, rng):
        a = rng.random((5, 7)).astype(np.float32)
        b = rng.random((7, 3)).astype(np.float32)
        np.testing.assert_allclose(linalg.gemm(a, b), a @ b, rtol=1e-4)
        np.testing.assert_allclose(
            linalg.gemm(a.T, b, trans_a=True), a @ b, rtol=1e-4
        )
        c = rng.random((5, 3)).astype(np.float32)
        np.testing.assert_allclose(
            linalg.gemm(a, b, alpha=2.0, beta=0.5, c=c), 2 * a @ b + 0.5 * c, rtol=1e-4
        )

    def test_gemv_axpy_dot(self, rng):
        a = rng.random((5, 7)).astype(np.float32)
        x = rng.random(7).astype(np.float32)
        y = rng.random(5).astype(np.float32)
        np.testing.assert_allclose(linalg.gemv(a, x), a @ x, rtol=1e-4)
        np.testing.assert_allclose(linalg.axpy(2.0, y, y), 3 * y, rtol=1e-5)
        np.testing.assert_allclose(linalg.dot(x, x), (x * x).sum(), rtol=1e-4)


class TestMatrixVector:
    def test_ops(self, rng):
        m = rng.random((4, 6)).astype(np.float32)
        v_col = rng.random(6).astype(np.float32) + 0.5
        v_row = rng.random(4).astype(np.float32) + 0.5
        np.testing.assert_allclose(
            linalg.binary_mult(m, v_col, True), m * v_col[None, :], rtol=1e-6
        )
        np.testing.assert_allclose(
            linalg.binary_div(m, v_row, False), m / v_row[:, None], rtol=1e-5
        )
        np.testing.assert_allclose(
            linalg.matrix_vector_op(m, v_col, jnp.add), m + v_col[None, :], rtol=1e-6
        )

    def test_div_skip_zero(self):
        m = np.ones((2, 3), np.float32)
        v = np.array([2.0, 0.0, 4.0], np.float32)
        out = linalg.binary_div_skip_zero(m, v, True, return_zero=True)
        np.testing.assert_allclose(out, [[0.5, 0, 0.25]] * 2, rtol=1e-6)


class TestDecompositions:
    def test_eig(self, rng):
        a = rng.standard_normal((8, 8))
        a = (a + a.T).astype(np.float64)
        v, w = linalg.eig_dc(a)
        np.testing.assert_allclose(np.asarray(v) @ np.diag(w) @ np.asarray(v).T, a, atol=1e-8)
        v2, w2 = linalg.eig_sel_dc(a, 3, smallest=True)
        assert v2.shape == (8, 3) and w2.shape == (3,)
        np.testing.assert_allclose(w2, np.sort(np.linalg.eigvalsh(a))[:3], atol=1e-8)

    def test_svd(self, rng):
        a = rng.standard_normal((10, 6)).astype(np.float64)
        u, s, v = linalg.svd_qr(a)
        np.testing.assert_allclose(linalg.svd_reconstruction(u, s, v), a, atol=1e-8)
        assert linalg.evaluate_svd_by_reconstruction(a, u, s, v)
        u2, s2, v2 = linalg.svd_eig(a)
        np.testing.assert_allclose(s2, s, atol=1e-6)
        np.testing.assert_allclose(linalg.svd_reconstruction(u2, s2, v2), a, atol=1e-6)

    def test_qr(self, rng):
        a = rng.standard_normal((8, 5)).astype(np.float64)
        q, r = linalg.qr_get_qr(a)
        np.testing.assert_allclose(np.asarray(q) @ np.asarray(r), a, atol=1e-10)
        np.testing.assert_allclose(np.asarray(q).T @ np.asarray(q), np.eye(5), atol=1e-10)

    def test_rsvd(self, rng):
        # Low-rank matrix: rsvd should recover it nearly exactly.
        u0 = rng.standard_normal((50, 5))
        v0 = rng.standard_normal((5, 30))
        a = (u0 @ v0).astype(np.float64)
        u, s, v = linalg.rsvd_fixed_rank(a, k=5, p=5, n_iters=3)
        np.testing.assert_allclose(linalg.svd_reconstruction(u, s, v), a, atol=1e-6)

    def test_lstsq(self, rng):
        a = rng.standard_normal((40, 6)).astype(np.float64)
        w_true = rng.standard_normal(6)
        b = a @ w_true
        for fn in (linalg.lstsq_svd_qr, linalg.lstsq_svd_jacobi,
                   linalg.lstsq_eig, linalg.lstsq_qr):
            np.testing.assert_allclose(fn(a, b), w_true, atol=1e-8, err_msg=str(fn))

    def test_cholesky_r1_update(self, rng):
        a = rng.standard_normal((6, 6))
        a = (a @ a.T + 6 * np.eye(6)).astype(np.float64)
        l_full = np.linalg.cholesky(a)
        l_sub = np.linalg.cholesky(a[:5, :5])
        x = a[:, 5][: 6]  # new column incl. diagonal
        l_up = linalg.cholesky_r1_update(l_sub, x)
        np.testing.assert_allclose(l_up, l_full, atol=1e-10)


class TestDecompositionGrids:
    """Shape/dtype property grids — the reference runs each factorization
    over parameter grids with per-dtype tolerance gates (cpp/test/linalg/
    eig.cu, svd.cu, qr.cu, rsvd.cu, lstsq.cu input grids)."""

    TOL = {np.float32: 1e-4, np.float64: 1e-10}

    @pytest.mark.parametrize("n", [2, 8, 33])
    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_eig_grid(self, rng, n, dtype):
        a = rng.standard_normal((n, n))
        a = (a + a.T).astype(dtype)
        for eig in (linalg.eig_dc, linalg.eig_jacobi):
            v, w = eig(a)
            v, w = np.asarray(v), np.asarray(w)
            tol = self.TOL[dtype] * n
            # ascending eigenvalues, orthonormal vectors, A v = w v
            assert np.all(np.diff(w) >= -tol)
            np.testing.assert_allclose(v.T @ v, np.eye(n), atol=tol)
            np.testing.assert_allclose(a @ v, v * w[None, :], atol=tol * 10)

    def test_eig_sel_largest(self, rng):
        a = rng.standard_normal((12, 12))
        a = (a + a.T).astype(np.float64)
        v, w = linalg.eig_sel_dc(a, 4, smallest=False)
        assert v.shape == (12, 4) and w.shape == (4,)
        np.testing.assert_allclose(w, np.sort(np.linalg.eigvalsh(a))[-4:],
                                   atol=1e-9)

    @pytest.mark.parametrize("m,n", [(10, 6), (6, 10), (16, 16), (40, 3)])
    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_svd_grid(self, rng, m, n, dtype):
        """svd_qr and svd_jacobi over tall/wide/square shapes: singular
        values match numpy, U/V have orthonormal columns, reconstruction
        holds."""
        a = rng.standard_normal((m, n)).astype(dtype)
        s_np = np.linalg.svd(a, compute_uv=False)
        tol = self.TOL[dtype] * max(m, n) * 10
        for svd in (linalg.svd_qr, linalg.svd_jacobi):
            u, s, v = svd(a)
            u, s, v = np.asarray(u), np.asarray(s), np.asarray(v)
            k = min(m, n)
            np.testing.assert_allclose(s, s_np, atol=tol)
            np.testing.assert_allclose(u.T @ u, np.eye(k), atol=tol)
            np.testing.assert_allclose(v.T @ v, np.eye(k), atol=tol)
            np.testing.assert_allclose(linalg.svd_reconstruction(
                jnp.asarray(u), jnp.asarray(s), jnp.asarray(v)), a, atol=tol)

    def test_svd_vector_flags(self, rng):
        a = rng.standard_normal((9, 4)).astype(np.float64)
        u, s, v = linalg.svd_qr(a, gen_left_vec=False, gen_right_vec=False)
        assert u is None and v is None and s.shape == (4,)

    def test_svd_eig_tall_skinny(self, rng):
        """svd_eig's Gram-matrix route matches svd_qr on its target shape
        (tall-skinny), including a rank-deficient case."""
        a = rng.standard_normal((60, 5)).astype(np.float64)
        u, s, v = linalg.svd_eig(a)
        np.testing.assert_allclose(s, np.linalg.svd(a, compute_uv=False),
                                   atol=1e-8)
        np.testing.assert_allclose(linalg.svd_reconstruction(u, s, v), a,
                                   atol=1e-8)
        # rank-deficient: column 4 = column 0 → smallest singular value 0
        a[:, 4] = a[:, 0]
        _, s2, _ = linalg.svd_eig(jnp.asarray(a))
        assert abs(float(s2[-1])) < 1e-6

    @pytest.mark.parametrize("m,n", [(8, 5), (5, 5), (30, 2)])
    def test_qr_grid(self, rng, m, n):
        a = rng.standard_normal((m, n)).astype(np.float64)
        q = np.asarray(linalg.qr_get_q(a))
        np.testing.assert_allclose(q.T @ q, np.eye(n), atol=1e-10)
        # Q spans col(a): projecting a onto Q reproduces a
        np.testing.assert_allclose(q @ (q.T @ a), a, atol=1e-10)

    def test_rsvd_perc(self, rng):
        u0 = rng.standard_normal((64, 8))
        v0 = rng.standard_normal((8, 40))
        a = (u0 @ v0).astype(np.float64)
        # 20% of min(64,40)=40 → k=8: exact recovery of the rank-8 matrix
        u, s, v = linalg.rsvd_perc(a, 0.2, p=5, n_iters=3)
        assert s.shape == (8,)
        np.testing.assert_allclose(linalg.svd_reconstruction(u, s, v), a,
                                   atol=1e-6)

    def test_rsvd_decaying_spectrum(self, rng):
        """Full-rank matrix with geometric spectrum decay: rsvd's top-k
        singular values match the exact ones (Halko guarantee regime)."""
        m, n, k = 50, 40, 6
        u0 = np.linalg.qr(rng.standard_normal((m, n)))[0]
        v0 = np.linalg.qr(rng.standard_normal((n, n)))[0]
        s0 = 2.0 ** -np.arange(n)
        a = (u0 * s0[None, :]) @ v0.T
        _, s, _ = linalg.rsvd_fixed_rank(jnp.asarray(a), k=k, p=10, n_iters=3)
        np.testing.assert_allclose(np.asarray(s), s0[:k], rtol=1e-6)

    def test_lstsq_overdetermined_noisy(self, rng):
        """With noise, all four engines agree with numpy's least-squares
        SOLUTION (not the generating weights) — the reference's lstsq.cu
        checks the same fixed point."""
        a = rng.standard_normal((50, 7)).astype(np.float64)
        b = a @ rng.standard_normal(7) + 0.1 * rng.standard_normal(50)
        w_np = np.linalg.lstsq(a, b, rcond=None)[0]
        for fn in (linalg.lstsq_svd_qr, linalg.lstsq_svd_jacobi,
                   linalg.lstsq_eig, linalg.lstsq_qr):
            np.testing.assert_allclose(np.asarray(fn(a, b)), w_np, atol=1e-8,
                                       err_msg=str(fn))

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_lstsq_dtype_grid(self, rng, dtype):
        a = rng.standard_normal((30, 4)).astype(dtype)
        w_true = rng.standard_normal(4).astype(dtype)
        b = a @ w_true
        tol = 1e-3 if dtype == np.float32 else 1e-9
        for fn in (linalg.lstsq_svd_qr, linalg.lstsq_qr):
            np.testing.assert_allclose(np.asarray(fn(a, b)), w_true, atol=tol,
                                       err_msg=str(fn))

    def test_cholesky_r1_update_chain(self, rng):
        """Growing a Cholesky factor one column at a time from 1x1 to full
        reproduces the direct factorization at every step (the incremental
        pattern cholesky_r1_update exists for)."""
        n = 8
        a = rng.standard_normal((n, n))
        a = a @ a.T + n * np.eye(n)
        l_cur = np.linalg.cholesky(a[:1, :1])
        for k in range(2, n + 1):
            l_cur = np.asarray(linalg.cholesky_r1_update(
                jnp.asarray(l_cur), jnp.asarray(a[:k, k - 1])))
            np.testing.assert_allclose(l_cur, np.linalg.cholesky(a[:k, :k]),
                                       atol=1e-9)


class TestTranspose:
    def test_transpose(self, rng):
        a = rng.random((3, 5)).astype(np.float32)
        np.testing.assert_array_equal(linalg.transpose(a), a.T)


class TestReviewRegressions:
    """Regression tests for code-review findings."""

    def test_lstsq_matrix_rhs(self, rng):
        a = rng.standard_normal((40, 6)).astype(np.float64)
        w_true = rng.standard_normal((6, 3))
        b = a @ w_true
        for fn in (linalg.lstsq_svd_qr, linalg.lstsq_eig, linalg.lstsq_qr):
            np.testing.assert_allclose(fn(a, b), w_true, atol=1e-8, err_msg=str(fn))

    def test_reduce_minmax_no_zero_clamp(self):
        neg = -np.ones((3, 4), np.float32)
        out = linalg.reduce(neg, Apply.ALONG_COLUMNS, reduce_op=jnp.maximum)
        np.testing.assert_allclose(out, [-1, -1, -1])
        pos = np.ones((3, 4), np.float32) * 5
        out = linalg.reduce(pos, Apply.ALONG_COLUMNS, reduce_op=jnp.minimum)
        np.testing.assert_allclose(out, [5, 5, 5])


def test_svd_jacobi_rank_deficient_tail_is_zero():
    """Jacobi SVD on an exactly rank-2 matrix returns (near-)zero trailing
    singular values — no spurious mass from the rotation sweeps."""
    from raft_tpu.linalg.decompositions import svd_jacobi

    rng = np.random.default_rng(1)
    m = rng.normal(0, 1, (8, 8)).astype(np.float32)
    rank2 = m[:, :2] @ rng.normal(0, 1, (2, 8)).astype(np.float32)
    u, s, v = svd_jacobi(rank2)
    s = np.asarray(s)
    assert (s[:2] > 1e-3).all()
    np.testing.assert_allclose(s[2:], 0.0, atol=1e-4)
