"""Lowering-contract locks (ISSUE 12): the dataflow value-flow engine +
laundering trios for the three ported rules, golden HLO fingerprints
(units, seeded regressions, the --update-goldens round-trip, determinism,
shipped-golden acceptance), the static retrace-closure certifier
(positive at HEAD, negative on a synthetic unbounded-static-arg module),
and the stale-exemption scan."""

import ast
import contextlib
import io
import json
import pathlib
import sys

import jax
import jax.numpy as jnp
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from raft_tpu.analysis import (  # noqa: E402
    dataflow,
    engine,
    fingerprint,
    registry,
    retrace,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def findings(posix, src, rule=None):
    out = engine.check_source(posix, src)
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


def flow_of(src):
    return dataflow.ValueFlow(ast.parse(src))


# ---------------------------------------------------------------------------
# the dataflow engine


class TestValueFlow:
    def test_import_alias_resolution(self):
        src = "import numpy as np\nx = np.asarray\n"
        f = flow_of(src)
        assign = ast.parse(src).body  # re-parse loses identity; use f's tree
        tree = f.module_scope.node
        val = tree.body[1].value  # np.asarray
        assert f.resolve(val) == "numpy.asarray"

    def test_assignment_chain(self):
        src = ("import jax\n"
               "a = jax.lax.psum\n"
               "b = a\n"
               "c = b\n")
        f = flow_of(src)
        tree = f.module_scope.node
        assert f.resolve(tree.body[3].value) == "jax.lax.psum"

    def test_tuple_unpacking(self):
        src = ("import numpy as np\n"
               "g, h = np.asarray, np.array\n"
               "u = g\nv = h\n")
        f = flow_of(src)
        tree = f.module_scope.node
        assert f.resolve(tree.body[2].value) == "numpy.asarray"
        assert f.resolve(tree.body[3].value) == "numpy.array"

    def test_from_import_alias(self):
        src = "from jax.lax import all_gather as ag\nx = ag\n"
        f = flow_of(src)
        tree = f.module_scope.node
        assert f.resolve(tree.body[1].value) == "jax.lax.all_gather"

    def test_helper_return(self):
        src = ("import numpy as np\n"
               "def _fetch():\n"
               "    return np.asarray\n"
               "x = _fetch()\n")
        f = flow_of(src)
        tree = f.module_scope.node
        assert f.resolve(tree.body[2].value) == "numpy.asarray"

    def test_class_bindings_do_not_leak_into_methods(self):
        # Python scoping: a class-body name is NOT visible in its methods
        src = ("import numpy as np\n"
               "class C:\n"
               "    g = np.asarray\n"
               "    def m(self, x):\n"
               "        return g(x)\n")
        f = flow_of(src)
        tree = f.module_scope.node
        call = tree.body[1].body[1].body[0].value
        assert f.resolve_call(call) is None

    def test_param_taint(self):
        src = ("import jax.numpy as jnp\n"
               "def dispatch(self, qb):\n"
               "    q = jnp.asarray(qb)\n"
               "    return q\n")
        f = flow_of(src)
        tree = f.module_scope.node
        ret = tree.body[1].body[1].value  # the returned `q`
        assert f.param_roots(ret) == {"qb"}

    def test_const_value_through_names(self):
        src = "_S = (1, 2)\nT = _S\n"
        f = flow_of(src)
        tree = f.module_scope.node
        assert f.const_value(tree.body[1].value) == (1, 2)

    def test_cycle_is_bounded(self):
        src = "a = b\nb = a\nx = a\n"
        f = flow_of(src)
        tree = f.module_scope.node
        assert f.resolve(tree.body[2].value) is None  # terminates, no hang


# ---------------------------------------------------------------------------
# laundering trios: the three dataflow-ported rules catch what the
# syntactic matchers miss — fire / fixed / marker for each laundering form


class TestHostTransferLaundering:
    def test_aliased_from_import_fires(self):
        src = ("from numpy import asarray as pull\n\n"
               "def _fused_em_scan(x):\n    return pull(x)\n")
        f = findings("raft_tpu/cluster/kmeans.py", src,
                     "hot-path-host-transfer")
        assert f and "laundered" in f[0].message

    def test_local_rebind_fires_at_call_line(self):
        src = ("import numpy as np\n\ndef deliver(x):\n"
               "    g = np.asarray\n    return g(x)\n")
        f = findings("raft_tpu/serve/engine.py", src,
                     "hot-path-host-transfer")
        assert [x.lineno for x in f] == [5]

    def test_helper_return_fires(self):
        src = ("import numpy as np\n\ndef _fetch():\n"
               "    return np.asarray\n\n\ndef dispatch(x):\n"
               "    return _fetch()(x)\n")
        assert findings("raft_tpu/serve/engine.py", src,
                        "hot-path-host-transfer")

    def test_fixed_form_passes(self):
        src = ("import numpy as np\n\ndef deliver(x):\n"
               "    return x\n")
        assert not findings("raft_tpu/serve/engine.py", src,
                            "hot-path-host-transfer")

    def test_marker_exempts_laundered_call(self):
        src = ("from numpy import asarray as pull\n\n"
               "def _fused_em_scan(x):\n"
               "    return pull(x)  "
               "# exempt(hot-path-host-transfer): (k,) table fetch\n")
        assert not findings("raft_tpu/cluster/kmeans.py", src,
                            "hot-path-host-transfer")

    def test_off_hot_path_laundering_passes(self):
        src = ("from numpy import asarray as pull\n\ndef f(x):\n"
               "    return pull(x)\n")
        assert not findings("raft_tpu/stats/mod.py", src,
                            "hot-path-host-transfer")


class TestCollectiveLaundering:
    def test_local_rebind_fires_at_call_line(self):
        src = ("import jax\n\ndef prog(x, a):\n"
               "    g = jax.lax.psum\n    return g(x, a)\n")
        f = findings("raft_tpu/neighbors/mod.py", src,
                     "collective-discipline")
        assert 5 in [x.lineno for x in f]          # the laundered CALL
        assert any("laundered" in x.message for x in f)

    def test_helper_return_fires(self):
        src = ("import jax\n\ndef _get():\n"
               "    return jax.lax.all_gather\n\n\ndef prog(x, a):\n"
               "    return _get()(x, a)\n")
        f = findings("raft_tpu/cluster/mod.py", src,
                     "collective-discipline")
        assert 8 in [x.lineno for x in f]   # the laundered CALL line

    def test_aliased_from_import_call_still_fires(self):
        src = ("from jax.lax import ppermute as shift\n\n"
               "def prog(x, a):\n    return shift(x, a, [(0, 1)])\n")
        f = findings("raft_tpu/neighbors/mod.py", src,
                     "collective-discipline")
        assert {1, 4} <= {x.lineno for x in f}

    def test_fixed_form_passes(self):
        src = ("def prog(comms, x):\n    return comms.allreduce(x)\n")
        assert not findings("raft_tpu/neighbors/mod.py", src,
                            "collective-discipline")

    def test_marker_on_call_line_exempts(self):
        src = ("import jax\n\ndef prog(x, a):\n"
               "    g = jax.lax.psum  "
               "# exempt(collective-discipline): counted by hand\n"
               "    return g(x, a)  "
               "# exempt(collective-discipline): counted by hand\n")
        assert not findings("raft_tpu/neighbors/mod.py", src,
                            "collective-discipline")

    def test_comms_home_laundering_allowed(self):
        src = ("import jax\n\ndef prog(x, a):\n"
               "    g = jax.lax.psum\n    return g(x, a)\n")
        assert not findings("raft_tpu/comms/mod.py", src,
                            "collective-discipline")


class TestDtypeDriftLaundering:
    def test_from_import_fires_at_import_and_use(self):
        src = ("from numpy import float64\n\ndef f(x):\n"
               "    return float64(x)\n")
        f = findings("raft_tpu/stats/mod.py", src, "dtype-drift")
        assert {1, 4} <= {x.lineno for x in f}  # import line + use line

    def test_local_rebind_fires_at_use(self):
        src = ("import jax.numpy as jnp\n\ndef f(x):\n"
               "    wide = jnp.float64\n    return x.astype(wide)\n")
        f = findings("raft_tpu/cluster/mod.py", src, "dtype-drift")
        assert 4 in [x.lineno for x in f]

    def test_x64_marker_at_hop_sanctions_uses(self):
        # the solver idiom: a conditional x64-gated rebind must not
        # re-fire at every later use of the name
        src = ("import jax.numpy as jnp\n\ndef f(x, c):\n"
               "    dt = jnp.float32\n"
               "    if c:\n"
               "        # x64: integer exactness requires f64 here\n"
               "        dt = jnp.float64\n"
               "    return x.astype(dt)\n")
        assert not findings("raft_tpu/solver/mod.py", src, "dtype-drift")

    def test_exempt_marker_at_hop_sanctions_uses(self):
        src = ("import numpy as np\n\ndef f(x):\n"
               "    wide = np.float64  "
               "# exempt(dtype-drift): host-side accumulator\n"
               "    return wide(x)\n")
        assert not findings("raft_tpu/stats/mod.py", src, "dtype-drift")

    def test_fixed_form_passes(self):
        src = ("import jax.numpy as jnp\n\ndef f(x):\n"
               "    return x.astype(jnp.float32)\n")
        assert not findings("raft_tpu/stats/mod.py", src, "dtype-drift")


# ---------------------------------------------------------------------------
# fingerprint units


_TOY_HLO = """
HloModule toy, input_output_alias={ {0}: (1, {}, may-alias) }
  %p = f32[8,64]{1,0} parameter(0)
  %c = f32[] constant(0)
  %f1 = f32[8,64]{1,0} fusion(f32[8,64]{1,0} %p), kind=kLoop
  %f2 = f32[8]{0} fusion(f32[8,64]{1,0} %f1), kind=kInput
  %d = f32[8,8]{1,0} dot(f32[8,64]{1,0} %f1, f32[8,64]{1,0} %f1)
  %ag = f32[2,8]{1,0} all-gather(f32[1,8]{1,0} %x), dimensions={0}
  %i = s32[8]{0} iota(), iota_dimension=0
  ROOT %t = (f32[8]{0}, s32[8]{0}) tuple(f32[8]{0} %f2, s32[8]{0} %i)
"""


class TestFingerprintUnits:
    def test_op_histogram(self):
        h = fingerprint.op_histogram(_TOY_HLO)
        assert h["fusion"] == 2
        assert h["dot"] == 1
        assert h["all-gather"] == 1
        # bookkeeping ops are structure-noise, excluded
        assert "parameter" not in h and "constant" not in h
        assert "tuple" not in h

    def test_dtype_set(self):
        assert fingerprint.dtype_set(_TOY_HLO) == ["f32", "s32"]

    def test_dumps_deterministic_no_timestamps(self):
        fp = {"schema": 1, "b": 2, "a": 1}
        s1, s2 = fingerprint.dumps(fp), fingerprint.dumps(dict(fp))
        assert s1 == s2
        assert s1.endswith("\n")
        assert json.loads(s1) == fp
        assert list(json.loads(s1)) == sorted(fp)  # sorted keys on disk


def _fp(**over):
    base = {
        "schema": fingerprint.SCHEMA, "program": "toy", "backend": "cpu",
        "ops": {"fusion": 20, "dot": 4, "add": 10},
        "fusions": 20, "collectives": 1, "collective_bytes": 4096,
        "dtypes": ["f32", "s32"], "donation_aliases": [[0, "may-alias"]],
        "transient_bytes": 1 << 20,
    }
    base.update(over)
    return base


class TestSeededRegressions:
    """The quarantine seeds: each regression class must FAIL the diff."""

    def test_clean_diff(self):
        assert fingerprint.diff(_fp(), _fp()) == []

    def test_extra_collective_fails(self):
        bad = _fp(collectives=2, collective_bytes=8192,
                  ops={"fusion": 20, "dot": 4, "add": 10})
        out = fingerprint.diff(_fp(), bad)
        assert any("collective launches" in f for f in out), out

    def test_collective_bytes_exact(self):
        out = fingerprint.diff(_fp(), _fp(collective_bytes=4097))
        assert any("payload" in f for f in out), out

    def test_broken_fusion_fails(self):
        # the fusion structure scattering into loose elementwise ops
        bad = _fp(fusions=5, ops={"fusion": 5, "dot": 4, "add": 40})
        out = fingerprint.diff(_fp(), bad)
        assert any("fusion count" in f for f in out), out

    def test_f64_upcast_fails(self):
        out = fingerprint.diff(_fp(), _fp(dtypes=["f32", "f64", "s32"]))
        assert any("dtype set" in f and "f64" in f for f in out), out

    def test_lost_compressed_path_fails(self):
        g = _fp(dtypes=["f32", "s32", "u8"])
        out = fingerprint.diff(g, _fp(dtypes=["f32", "s32"]))
        assert any("lost" in f for f in out), out

    def test_dropped_donation_fails(self):
        out = fingerprint.diff(_fp(), _fp(donation_aliases=[]))
        assert any("alias" in f for f in out), out

    def test_small_op_jitter_within_tolerance_passes(self):
        ok = _fp(ops={"fusion": 20, "dot": 4, "add": 12})  # +2 abs slack
        assert fingerprint.diff(_fp(), ok) == []

    def test_transient_tolerance(self):
        assert fingerprint.diff(_fp(), _fp(
            transient_bytes=int(1.2 * (1 << 20)))) == []
        out = fingerprint.diff(_fp(), _fp(transient_bytes=2 << 20))
        assert any("transient" in f for f in out), out

    def test_schema_mismatch_is_a_finding(self):
        out = fingerprint.diff(_fp(schema=0), _fp())
        assert any("schema" in f for f in out), out


def _toy_entry(name="toy.fp", regress=False):
    def clean(x):
        return (x @ x.T).sum(axis=0)

    def upcast(x):
        # the seeded dtype regression: bf16 appears in the module
        return (x @ x.T).astype(jnp.bfloat16).astype(jnp.float32).sum(axis=0)

    fn = upcast if regress else clean
    return registry.ProgramEntry(
        name=name, builder=lambda: dict(fn=fn, args=(
            jax.ShapeDtypeStruct((64, 64), jnp.float32),)))


class TestGoldenRoundTrip:
    """update → clean diff → seeded regression → failing diff → update →
    clean: the whole --update-goldens flow on a toy registry."""

    def _run(self, monkeypatch, tmp_path, entry, **kw):
        monkeypatch.setattr(registry, "iter_programs",
                            lambda fast_only=False: [entry])
        return fingerprint.run(out=io.StringIO(),
                               golden_dir=tmp_path / "goldens", **kw)

    def test_round_trip(self, monkeypatch, tmp_path):
        clean = _toy_entry()
        # 1. no golden yet: the diff FAILS asking for --update-goldens
        _, failed = self._run(monkeypatch, tmp_path, clean)
        assert failed >= 1
        # 2. update writes the golden...
        reports, failed = self._run(monkeypatch, tmp_path, clean,
                                    update=True)
        assert failed == 0 and reports[0].status == "updated"
        golden_file = tmp_path / "goldens" / "toy.fp.json"
        assert golden_file.is_file()
        # ...deterministically: a second update is byte-identical
        before = golden_file.read_bytes()
        self._run(monkeypatch, tmp_path, clean, update=True)
        assert golden_file.read_bytes() == before
        # 3. clean diff against the committed golden
        reports, failed = self._run(monkeypatch, tmp_path, clean)
        # (floor applies to full runs; toy registry has 1 program)
        assert reports[0].status == "ok", reports[0].findings
        # 4. the seeded regression (bf16 appearing) FAILS the gate
        reports, _ = self._run(monkeypatch, tmp_path, _toy_entry(
            regress=True))
        assert reports[0].status == "fail"
        assert any("dtype set" in f for f in reports[0].findings)
        # 5. --update-goldens restores a clean run for the new lowering
        self._run(monkeypatch, tmp_path, _toy_entry(regress=True),
                  update=True)
        reports, _ = self._run(monkeypatch, tmp_path, _toy_entry(
            regress=True))
        assert reports[0].status == "ok", reports[0].findings

    def test_stale_golden_fails_and_update_prunes(self, monkeypatch,
                                                  tmp_path):
        clean = _toy_entry()
        self._run(monkeypatch, tmp_path, clean, update=True)
        orphan = tmp_path / "goldens" / "toy.renamed_away.json"
        orphan.write_text(fingerprint.dumps(_fp()))
        reports, failed = self._run(monkeypatch, tmp_path, clean)
        assert any(r.name == "toy.renamed_away" and r.status == "fail"
                   for r in reports)
        self._run(monkeypatch, tmp_path, clean, update=True)
        assert not orphan.exists()  # update prunes orphaned goldens

    def test_backend_mismatch_skips(self, monkeypatch, tmp_path):
        clean = _toy_entry()
        self._run(monkeypatch, tmp_path, clean, update=True)
        golden_file = tmp_path / "goldens" / "toy.fp.json"
        g = json.loads(golden_file.read_text())
        g["backend"] = "tpu"
        golden_file.write_text(fingerprint.dumps(g))
        reports, failed = self._run(monkeypatch, tmp_path, clean)
        assert reports[0].status == "skipped"

    def test_strict_counts_skips(self, monkeypatch, tmp_path):
        needy = registry.ProgramEntry(
            name="toy.needs_mesh", builder=lambda: dict(),
            requires_devices=10 ** 6)
        _, failed = self._run(monkeypatch, tmp_path, needy, strict=True)
        assert failed >= 1
        _, failed = self._run(monkeypatch, tmp_path, needy, strict=False)
        # only the floor can fail a skipped-only run without strict
        assert all("skipped" == r.status for r in
                   self._run(monkeypatch, tmp_path, needy)[0])


@contextlib.contextmanager
def _x64_off():
    """The committed goldens are recorded in the CI environment (x64
    off — the CLI default); the test session runs x64 ON (conftest), so
    golden comparisons extract under the goldens' environment."""
    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", False)
    try:
        yield
    finally:
        jax.config.update("jax_enable_x64", prev)


class TestShippedGoldens:
    def test_every_registered_program_has_a_committed_golden(self):
        for e in registry.iter_programs():
            assert fingerprint.golden_path(e.name).is_file(), e.name

    def test_goldens_are_deterministic_serializations(self):
        # committed artifacts are byte-exact re-serializations: sorted
        # keys, no timestamps, trailing newline (the review-surface
        # contract) — recorded for the CI environment (cpu, x64 off)
        for p in sorted(fingerprint.GOLDEN_DIR.glob("*.json")):
            raw = p.read_text()
            assert raw == fingerprint.dumps(json.loads(raw)), p.name
            g = json.loads(raw)
            assert g["backend"] == "cpu" and g["x64"] is False, p.name

    @pytest.mark.slow  # re-lowers every fast-subset program (~24s cold);
    # checks.sh --fingerprints --strict diffs the FULL registry every run
    def test_fast_subset_diffs_clean_at_head(self):
        # the single-device programs re-fingerprint and diff clean in-test
        # (the full 10-program pass incl. the 8-device sharded entries is
        # CI's job: checks.sh --fingerprints --strict)
        with _x64_off():
            for e in registry.iter_programs(fast_only=True):
                fp = fingerprint.extract(e)
                golden = json.loads(
                    fingerprint.golden_path(e.name).read_text())
                assert fingerprint.diff(golden, fp) == [], e.name

    @pytest.mark.slow  # tier-1 budget (ISSUE-20 rebalance): ci/checks.sh
    # `--fingerprints --strict` diffs every committed golden (incl. this
    # one) on every CI run
    def test_sharded_ivf_pq_golden_one_allgather(self, devices):
        # the new third sharded backend: its committed golden pins the
        # one-allgather contract exactly
        golden = json.loads(fingerprint.golden_path(
            "ann_mnmg.ivf_pq_sharded").read_text())
        assert golden["collectives"] == 1
        assert golden["collective_bytes"] == 8 * 64 * 2 * 8 * 4
        with _x64_off():
            fp = fingerprint.extract(registry.get_program(
                "ann_mnmg.ivf_pq_sharded"))
        assert fingerprint.diff(golden, fp) == []

    def test_programs_filter_honored(self):
        # the --programs contract extends to the fingerprint pass: only
        # the named program is fingerprinted (and the full-run-only
        # checks — floor, stale goldens — stay out of filtered runs)
        with _x64_off():
            out = io.StringIO()
            reports, failed = fingerprint.run(["ivf_pq.csum_tile"],
                                              out=out)
        assert [r.name for r in reports] == ["ivf_pq.csum_tile"]
        assert failed == 0
        assert "knn_scan" not in out.getvalue()

    def test_unknown_program_name_raises(self):
        with pytest.raises(KeyError):
            fingerprint.run(["no.such_program"], out=io.StringIO())

    def test_x64_mismatch_skips_not_fails(self, monkeypatch, tmp_path):
        # a golden recorded under another x64 setting must be SKIPPED —
        # comparing lowerings across environments is noise, not signal
        entry = _toy_entry()
        monkeypatch.setattr(registry, "iter_programs",
                            lambda fast_only=False: [entry])
        gdir = tmp_path / "goldens"
        fingerprint.run(update=True, out=io.StringIO(), golden_dir=gdir)
        g = json.loads((gdir / "toy.fp.json").read_text())
        g["x64"] = not g["x64"]
        (gdir / "toy.fp.json").write_text(fingerprint.dumps(g))
        reports, failed = fingerprint.run(out=io.StringIO(),
                                          golden_dir=gdir)
        assert reports[0].status == "skipped"


# ---------------------------------------------------------------------------
# the retrace certifier


class TestRetraceCertifier:
    def test_head_closure_certified(self):
        # the acceptance contract: serve steady-state signature closure
        # PROVEN at HEAD — every obligation ok, zero failures
        reports, failed = retrace.run(out=io.StringIO())
        assert failed == 0, [
            (r.name, r.findings) for r in reports if r.status == "fail"]
        names = {r.name for r in reports}
        # the certificate actually covers the serving layer
        assert any(n.startswith("serve.warm_dispatch._") for n in names)
        assert "serve.backends_cover" in names
        assert any(n.startswith("serve.bucket_closure") for n in names)
        assert "retrace.static_cardinality" in names

    def test_every_backend_class_certified(self):
        reports, _ = retrace.run(out=io.StringIO())
        certified = {r.name.rsplit(".", 1)[-1] for r in reports
                     if r.name.startswith("serve.warm_dispatch.")}
        for cls in ("_BruteForceBackend", "_IvfFlatBackend",
                    "_IvfPqBackend", "_ShardedBackend", "ShardedSearcher"):
            assert cls in certified, certified

    def test_mutate_closure_certified(self):
        # ISSUE 20: the mutable-index obligations prove mask-in-scan,
        # ladder-bounded bitmaps, write-path rewarm, locked dispatch and
        # refresh-only promotion at HEAD
        reports, failed = retrace.run(["mutate_closure"],
                                      out=io.StringIO())
        assert failed == 0, [
            (r.name, r.findings) for r in reports if r.status == "fail"]
        names = {r.name for r in reports}
        for ob in ("mask_in_scan", "families_thread_mask",
                   "tomb_buckets_via_ladder", "writes_rewarm_signatures",
                   "dispatch_snapshots_under_lock",
                   "compact_promotes_via_refresh", "backend_registered"):
            assert f"serve.mutate_closure.{ob}" in names, names

    def test_synthetic_unbounded_static_arg_flagged(self, tmp_path):
        (tmp_path / "leaky.py").write_text(
            "from raft_tpu.core.aot import aot\n\n"
            "def fn(q, n):\n    return q[:n]\n\n"
            "F = aot(fn, static_argnums=(1,))\n\n"
            "def serve(q):\n"
            "    return F(q, q.shape[0])\n")
        reports, failed = retrace.run(
            ["static_cardinality"], roots=[str(tmp_path)],
            out=io.StringIO())
        assert failed == 1
        assert any("unbounded" in f for f in reports[-1].findings)

    def test_bucket_dim_bounds_the_same_module(self, tmp_path):
        (tmp_path / "fixed.py").write_text(
            "from raft_tpu.core.aot import aot, _bucket_dim\n\n"
            "def fn(q, n):\n    return q[:n]\n\n"
            "F = aot(fn, static_argnums=(1,))\n\n"
            "def serve(q):\n"
            "    return F(q, _bucket_dim(q.shape[0]))\n")
        _, failed = retrace.run(["static_cardinality"],
                                roots=[str(tmp_path)], out=io.StringIO())
        assert failed == 0

    def test_min_against_cap_bounds(self, tmp_path):
        (tmp_path / "capped.py").write_text(
            "from raft_tpu.core.aot import aot\n\n"
            "def fn(q, t):\n    return q[:t]\n\n"
            "F = aot(fn, static_argnums=(1,))\n\n"
            "def serve(q):\n"
            "    return F(q, min(16384, q.shape[0]))\n")
        _, failed = retrace.run(["static_cardinality"],
                                roots=[str(tmp_path)], out=io.StringIO())
        assert failed == 0

    def test_len_is_unbounded(self, tmp_path):
        (tmp_path / "leaky2.py").write_text(
            "from raft_tpu.core.aot import aot\n\n"
            "def fn(q, n):\n    return q[:n]\n\n"
            "F = aot(fn, static_argnums=(1,))\n\n"
            "def serve(batches):\n"
            "    return F(batches, len(batches))\n")
        _, failed = retrace.run(["static_cardinality"],
                                roots=[str(tmp_path)], out=io.StringIO())
        assert failed == 1

    def test_verbatim_param_passthrough_is_callers_cardinality(
            self, tmp_path):
        (tmp_path / "keyed.py").write_text(
            "from raft_tpu.core.aot import aot\n\n"
            "def fn(q, k):\n    return q[:k]\n\n"
            "F = aot(fn, static_argnums=(1,))\n\n"
            "def knn(q, k):\n"
            "    return F(q, k)\n")
        _, failed = retrace.run(["static_cardinality"],
                                roots=[str(tmp_path)], out=io.StringIO())
        assert failed == 0

    def test_coercion_rebind_is_bounded(self, tmp_path):
        # the pairwise.py idiom: metric = DistanceType(metric) re-binds a
        # caller-owned param through an enum coercion
        (tmp_path / "coerce.py").write_text(
            "from raft_tpu.core.aot import aot\n\n"
            "def fn(q, m, a):\n    return q\n\n"
            "F = aot(fn, static_argnums=(2, 3))\n\n"
            "def distance(x, metric, arg):\n"
            "    metric = DistanceType(metric)\n"
            "    arg = float(arg)\n"
            "    return F(x, x, metric, arg)\n")
        _, failed = retrace.run(["static_cardinality"],
                                roots=[str(tmp_path)], out=io.StringIO())
        assert failed == 0

    def test_exempt_marker_sanctions(self, tmp_path):
        (tmp_path / "sanctioned.py").write_text(
            "from raft_tpu.core.aot import aot\n\n"
            "def fn(q, n):\n    return q[:n]\n\n"
            "F = aot(fn, static_argnums=(1,))\n\n"
            "def rebuild(q):\n"
            "    # exempt(retrace-unbounded-static): one-shot build path\n"
            "    return F(q, q.shape[0])\n")
        _, failed = retrace.run(["static_cardinality"],
                                roots=[str(tmp_path)], out=io.StringIO())
        assert failed == 0

    def test_names_filter(self):
        reports, _ = retrace.run(["bucket_closure"], out=io.StringIO())
        assert reports
        assert all("bucket_closure" in r.name for r in reports)

    def test_incongruent_warm_dispatch_fails(self, monkeypatch, tmp_path):
        # a backend whose dispatch passes a static warm() never lowered:
        # the congruence certificate must fail
        mod = tmp_path / "engine.py"
        mod.write_text(
            "import jax\n\n"
            "class _LeakyBackend:\n"
            "    def warm(self, bucket, dtype):\n"
            "        self.fn.compiled(*self._args(\n"
            "            jax.ShapeDtypeStruct((bucket, self.dim), dtype)))\n"
            "    def dispatch(self, qb):\n"
            "        return self.fn(*self._args(qb), qb.dtype)\n")
        import ast as ast_mod

        tree = ast_mod.parse(mod.read_text())
        flow = dataflow.ValueFlow(tree)
        reports = retrace.certify_warm_dispatch(
            {"engine.py": tree}, {"engine.py": flow})
        leaky = [r for r in reports
                 if r.name == "serve.warm_dispatch._LeakyBackend"]
        assert leaky and leaky[0].status == "fail"

    def test_missing_warm_fails(self):
        import ast as ast_mod

        src = ("class _NoWarm:\n"
               "    def dispatch(self, qb):\n"
               "        return self.fn(qb)\n")
        tree = ast_mod.parse(src)
        reports = retrace.certify_warm_dispatch(
            {"m.py": tree}, {"m.py": dataflow.ValueFlow(tree)})
        assert reports and reports[0].status == "fail"


# ---------------------------------------------------------------------------
# stale-exemption scan


class TestStaleExemptions:
    def test_stale_marker_reported(self):
        src = ("def f(v):\n"
               "    return v + 1  # exempt(raw-segment-sum): outdated\n")
        stale = engine.scan_stale_source("raft_tpu/x/mod.py", src)
        assert len(stale) == 1
        assert stale[0].rules == ("raw-segment-sum",)

    def test_live_marker_not_reported(self):
        src = ("import jax\n\n\ndef f(v, i):\n"
               "    return jax.ops.segment_sum(v, i, num_segments=4)"
               "  # exempt(raw-segment-sum): engine baseline\n")
        assert not engine.scan_stale_source("raft_tpu/x/mod.py", src)

    def test_marker_above_live_finding_not_reported(self):
        src = ("import jax\n\n\ndef f(v, i):\n"
               "    # exempt(raw-segment-sum): sanctioned here\n"
               "    return jax.ops.segment_sum(v, i, num_segments=4)\n")
        assert not engine.scan_stale_source("raft_tpu/x/mod.py", src)

    def test_marker_inside_string_literal_not_scanned(self):
        # quarantine tests quote markers in snippets — not markers
        src = ('SRC = "x = 1  # exempt(raw-segment-sum): quoted"\n')
        assert not engine.scan_stale_source("tests/test_x.py", src)

    def test_legacy_spelling_scanned_via_mapping(self):
        src = ("def f(x):\n"
               "    return x  # host-ok: stale legacy marker\n")
        stale = engine.scan_stale_source(
            "raft_tpu/neighbors/ann_mnmg.py", src)
        assert stale and stale[0].rules == ("hot-path-host-transfer",)

    def test_partially_live_comma_list_kept(self):
        src = ("import jax\n\n\ndef f(v, i):\n"
               "    return jax.ops.segment_sum(v, i, num_segments=4)"
               "  # exempt(raw-segment-sum, dtype-drift): shared\n")
        assert not engine.scan_stale_source("raft_tpu/x/mod.py", src)

    def test_unknown_rule_id_not_staleness(self):
        # a typo'd id is exemption-hygiene's problem, not staleness
        src = ("def f(x):\n"
               "    return x  # exempt(no-such-rule): typo\n")
        assert not engine.scan_stale_source("raft_tpu/x/mod.py", src)

    # `slow` since ISSUE-19: the identical shipped-tree scan runs as a
    # warning pass in every ci/checks.sh invocation (budget rebalance)
    @pytest.mark.slow
    def test_shipped_tree_has_no_stale_markers(self):
        n = engine.scan_stale_exemptions(out=io.StringIO())
        assert n == 0


# (fast-tier registration lives in tests/conftest.py::_FAST_TESTS —
# test_head_closure_certified + the committed-golden catalog check)
