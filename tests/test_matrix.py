"""Matrix primitive tests — counterpart of reference cpp/test/matrix/*."""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import matrix


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def test_argmax_argmin(rng):
    m = rng.standard_normal((6, 9)).astype(np.float32)
    np.testing.assert_array_equal(matrix.argmax(m), m.argmax(axis=1))
    np.testing.assert_array_equal(matrix.argmin(m), m.argmin(axis=1))


def test_col_wise_sort(rng):
    m = rng.standard_normal((8, 4)).astype(np.float32)
    np.testing.assert_allclose(matrix.col_wise_sort(m), np.sort(m, axis=0), rtol=1e-6)
    s, idx = matrix.col_wise_sort(m, return_indices=True)
    np.testing.assert_allclose(np.take_along_axis(m, np.asarray(idx), axis=0), s, rtol=1e-6)


def test_diagonal(rng):
    m = rng.random((5, 5)).astype(np.float32)
    np.testing.assert_allclose(matrix.diagonal(m), np.diag(m), rtol=1e-6)
    out = matrix.set_diagonal(jnp.asarray(m), jnp.zeros(5))
    assert np.allclose(np.diag(np.asarray(out)), 0)
    inv = matrix.matrix_diagonal_inverse(jnp.asarray(m))
    np.testing.assert_allclose(np.diag(np.asarray(inv)), 1 / np.diag(m), rtol=1e-5)


def test_gather(rng):
    m = rng.random((10, 3)).astype(np.float32)
    idx = np.array([2, 2, 0, 7])
    np.testing.assert_allclose(matrix.gather(m, idx), m[idx], rtol=1e-6)
    stencil = np.array([1.0, -1.0, 1.0, -1.0], np.float32)
    out = matrix.gather_if(m, idx, stencil, lambda s: s > 0, fallback=-5.0)
    expected = m[idx].copy()
    expected[1] = -5.0
    expected[3] = -5.0
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_linewise_op(rng):
    m = rng.random((4, 6)).astype(np.float32)
    v = rng.random(6).astype(np.float32)
    np.testing.assert_allclose(
        matrix.linewise_op(m, v, jnp.add, along_lines=True), m + v[None, :], rtol=1e-6
    )


def test_math(rng):
    m = rng.random((3, 4)).astype(np.float32) + 0.1
    np.testing.assert_allclose(matrix.power(m), m * m, rtol=1e-6)
    np.testing.assert_allclose(matrix.seq_root(m), np.sqrt(m), rtol=1e-6)
    np.testing.assert_allclose(matrix.ratio(m), m / m.sum(), rtol=1e-5)
    np.testing.assert_allclose(matrix.reciprocal(m), 1 / m, rtol=1e-5)
    np.testing.assert_allclose(matrix.sq_norm(m), (m * m).sum(), rtol=1e-5)


def test_reciprocal_zero_guard():
    m = np.array([[2.0, 0.0]], np.float32)
    out = matrix.reciprocal(m, set_zero=True)
    np.testing.assert_allclose(out, [[0.5, 0.0]], rtol=1e-6)


def test_sign_flip(rng):
    m = rng.standard_normal((6, 4)).astype(np.float32)
    out = np.asarray(matrix.sign_flip(m))
    for j in range(4):
        i = np.abs(out[:, j]).argmax()
        assert out[i, j] > 0
    # Flip preserves column subspace
    np.testing.assert_allclose(np.abs(out), np.abs(m), rtol=1e-6)


def test_reverse_slice_triangular(rng):
    m = rng.random((6, 6)).astype(np.float32)
    np.testing.assert_allclose(matrix.reverse(m, axis=0), m[::-1], rtol=1e-6)
    np.testing.assert_allclose(matrix.slice_matrix(m, 1, 2, 4, 5), m[1:4, 2:5], rtol=1e-6)
    np.testing.assert_allclose(matrix.upper_triangular(m), np.triu(m), rtol=1e-6)
    from raft_tpu.core import LogicError

    with pytest.raises(LogicError):
        matrix.slice_matrix(m, 0, 0, 7, 2)


def test_threshold():
    m = np.array([[0.001, 0.5], [-0.002, -2.0]], np.float32)
    out = matrix.threshold(m, 0.01)
    np.testing.assert_allclose(out, [[0, 0.5], [0, -2.0]], rtol=1e-6)


def test_init_and_print(capsys):
    np.testing.assert_array_equal(matrix.eye(3), np.eye(3, dtype=np.float32))
    np.testing.assert_array_equal(matrix.fill((2, 2), 7.0), np.full((2, 2), 7.0, np.float32))
    text = matrix.print_matrix(np.array([[1.0, 2.0]]), name="m")
    assert "1 2" in text


class TestOpsOracleSweep:
    """Numpy-oracle sweep over the remaining ops surface (reference
    matrix tests parameterize sizes/dtypes the same way,
    test/matrix/*.cu)."""

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    @pytest.mark.parametrize("shape", [(7, 5), (64, 33), (1, 9)])
    def test_gather_if_matches_masked_gather(self, dtype, shape):
        from raft_tpu.matrix import ops

        rng = np.random.default_rng(shape[0])
        m = rng.normal(0, 1, shape).astype(dtype)
        idx = rng.integers(0, shape[0], 5)
        stencil = rng.normal(0, 1, 5).astype(dtype)
        out = np.asarray(ops.gather_if(m, idx, stencil,
                                       lambda s: s > 0, fallback=-1.0))
        exp = np.where((stencil > 0)[:, None], m[idx], -1.0)
        np.testing.assert_allclose(out, exp.astype(dtype))

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_truncate_eye_fill_sqnorm(self, dtype):
        from raft_tpu.matrix import ops

        rng = np.random.default_rng(1)
        m = rng.normal(0, 1, (10, 6)).astype(dtype)
        np.testing.assert_allclose(np.asarray(ops.truncate_rows(m, 4)), m[:4])
        np.testing.assert_allclose(np.asarray(ops.eye(3, 5, dtype)),
                                   np.eye(3, 5, dtype=dtype))
        np.testing.assert_allclose(np.asarray(ops.fill((2, 3), 7.0, dtype)),
                                   np.full((2, 3), 7.0, dtype))
        np.testing.assert_allclose(float(ops.sq_norm(m)), (m * m).sum(),
                                   rtol=1e-5)

    def test_set_diagonal_and_inverse(self):
        from raft_tpu.matrix import ops

        rng = np.random.default_rng(2)
        m = rng.normal(0, 1, (5, 5)).astype(np.float32)
        v = np.arange(1.0, 6.0, dtype=np.float32)
        out = np.asarray(ops.set_diagonal(m, v))
        np.testing.assert_allclose(np.diag(out), v)
        inv = np.asarray(ops.matrix_diagonal_inverse(np.diag(v)))
        np.testing.assert_allclose(np.diag(inv), 1.0 / v, rtol=1e-6)

    def test_seq_root_ratio_weighted(self):
        from raft_tpu.matrix import ops

        rng = np.random.default_rng(3)
        m = np.abs(rng.normal(0, 1, (4, 6))).astype(np.float32) + 0.1
        np.testing.assert_allclose(np.asarray(ops.seq_root(m)), np.sqrt(m),
                                   rtol=1e-6)
        r = np.asarray(ops.ratio(m))
        np.testing.assert_allclose(r, m / m.sum(), rtol=1e-5)
        w = np.abs(rng.normal(0, 1, m.shape)).astype(np.float32)  # elementwise
        wr = np.asarray(ops.weighted_ratio(m, w))
        np.testing.assert_allclose(wr, m / (m * w).sum(), rtol=1e-5)


class TestSelectKGrid:
    """select_k property grid at the shapes/edge cases the reference's
    three-engine selection family tests cover (matrix/select_k.cuh,
    topk/warpsort vs radix tests): k extremes, duplicate values, payload
    carry, both directions, multiple dtypes."""

    @pytest.mark.parametrize("nq,n,k", [(1, 1, 1), (4, 100, 1), (4, 100, 100),
                                        (16, 257, 7), (3, 1024, 64)])
    @pytest.mark.parametrize("select_min", [True, False])
    def test_vs_numpy_sort(self, nq, n, k, select_min):
        from raft_tpu.matrix import select_k

        rng = np.random.default_rng(nq * 1000 + n + k)
        x = rng.standard_normal((nq, n)).astype(np.float32)
        vals, idx = select_k(x, k, select_min=select_min)
        vals, idx = np.asarray(vals), np.asarray(idx)
        want = np.sort(x, axis=1)[:, :k] if select_min \
            else -np.sort(-x, axis=1)[:, :k]
        np.testing.assert_allclose(vals, want, rtol=1e-6)
        # returned indices must address the returned values
        np.testing.assert_allclose(np.take_along_axis(x, idx, axis=1), vals,
                                   rtol=1e-6)

    def test_duplicate_values_indices_valid(self):
        """With massive ties the k selected values are still correct and
        each returned index addresses a matching element (the reference
        permits any tie order; so do we)."""
        from raft_tpu.matrix import select_k

        x = np.tile(np.array([[2.0, 1.0, 1.0, 1.0, 3.0]], np.float32),
                    (3, 1))
        vals, idx = select_k(x, 3)
        np.testing.assert_allclose(np.asarray(vals),
                                   [[1.0, 1.0, 1.0]] * 3)
        picked = np.take_along_axis(x, np.asarray(idx), axis=1)
        np.testing.assert_allclose(picked, np.asarray(vals))
        assert all(len(set(row.tolist())) == 3 for row in np.asarray(idx))

    def test_payload_carry_roundtrip(self):
        """Custom indices payload rides along (the IVF merge use-case:
        payload = global ids, values = distances)."""
        from raft_tpu.matrix import select_k

        rng = np.random.default_rng(0)
        x = rng.standard_normal((5, 40)).astype(np.float32)
        payload = rng.integers(0, 10**6, (5, 40)).astype(np.int32)
        vals, got_payload = select_k(x, 6, indices=payload)
        order = np.argsort(x, axis=1)[:, :6]
        np.testing.assert_array_equal(np.asarray(got_payload),
                                      np.take_along_axis(payload, order,
                                                         axis=1))

    def test_select_min_max_aliases(self):
        from raft_tpu.matrix import select_k, select_max_k, select_min_k

        x = np.random.default_rng(1).standard_normal((4, 32)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(select_min_k(x, 5)[0]),
                                      np.asarray(select_k(x, 5)[0]))
        np.testing.assert_array_equal(
            np.asarray(select_max_k(x, 5)[0]),
            np.asarray(select_k(x, 5, select_min=False)[0]))

    def test_1d_input_single_query(self):
        """A 1-D values vector selects along its only axis (lax.top_k
        semantics) — pinned so a future engine swap keeps the contract."""
        from raft_tpu.matrix import select_k

        x = np.arange(10, dtype=np.float32)[::-1].copy()
        vals, idx = select_k(x, 3)
        np.testing.assert_allclose(np.asarray(vals).ravel(), [0.0, 1.0, 2.0])
        np.testing.assert_array_equal(np.asarray(idx).ravel(), [9, 8, 7])
