"""Mutable index: delete/upsert/compaction vs a rebuild oracle (ISSUE 20).

Property grid {ivf_flat, ivf_pq} × {f32, bf16} × {world 1, 2}: every
combination runs the same churn script (replace, delete, insert, duplicate
re-upsert) and is judged against a from-scratch rebuild of exactly the
live rows.  For ivf_flat at FULL probe coverage the merged main∪delta
search must be bit-identical in distances to the oracle (probe selection
is removed, so clustering differences cannot leak in — the docs/
mutable_index.md §identity contract); for ivf_pq the oracle retrains its
codebooks, so the sharp properties are live-set discipline (a deleted id
NEVER appears, every returned id is live) and delta self-retrieval.

The serving-side battery drives a warmed ``ServeEngine`` concurrently
with writes and an injected ``refresh`` fault (the swap-atomicity crash
window) — zero failed requests throughout, and the post-fault engine
still promotes a clean compaction.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.comms import build_comms
from raft_tpu.core.aot import aot_compile_counters
from raft_tpu.neighbors import ann_mnmg, ivf_flat, ivf_pq, mutable
from raft_tpu.testing import faults

_N, _DIM, _K, _LISTS = 1536, 24, 8, 8

_COMMS = {}


def _comms(world):
    if world not in _COMMS:
        from jax.sharding import Mesh

        _COMMS[world] = build_comms(
            Mesh(np.array(jax.devices()[:world]), ("world",)))
    return _COMMS[world]


def _params(kind):
    if kind == "ivf_flat":
        return ivf_flat.IndexParams(n_lists=_LISTS, kmeans_n_iters=4,
                                    seed=1)
    return ivf_pq.IndexParams(n_lists=_LISTS, pq_dim=8, pq_bits=8,
                              kmeans_n_iters=4, seed=1)


def _family(kind):
    return ivf_flat if kind == "ivf_flat" else ivf_pq


def _data(dtype, seed=0, n=_N):
    rng = np.random.default_rng(seed)
    x = rng.random((n, _DIM)).astype(np.float32)
    return jnp.asarray(x, jnp.dtype(dtype))


def _build_mut(kind, dtype, world, seed=0):
    """(MutableIndex, live-oracle dict id → np row) for one grid point."""
    bp = _params(kind)
    x = _data(dtype, seed=seed)
    ids = np.arange(_N, dtype=np.int64)
    if world > 1:
        main = _family(kind).build_sharded(bp, x, _comms(world),
                                           ids=jnp.asarray(ids, jnp.int32))
        mut = mutable.MutableIndex(main, x, ids, build_params=bp,
                                   comms=_comms(world))
    else:
        main = _family(kind).build(bp, x, ids=jnp.asarray(ids, jnp.int32))
        mut = mutable.MutableIndex(main, x, ids, build_params=bp)
    live = {int(j): np.asarray(x[r], np.float32)
            for r, j in enumerate(ids)}
    return mut, live


def _churn(mut, live, dtype, seed=1):
    """The shared churn script; mirrors every op into *live* (the test's
    INDEPENDENT oracle bookkeeping, deliberately not mut.live_rows())."""
    rng = np.random.default_rng(seed)

    def rows(n):
        return jnp.asarray(rng.random((n, _DIM)).astype(np.float32),
                           mut_dtype)

    mut_dtype = jnp.dtype(dtype)
    # replace 192 existing rows
    rep = np.arange(0, 192, dtype=np.int64)
    v = rows(rep.size)
    mut.upsert(v, rep)
    for r, j in enumerate(rep):
        live[int(j)] = np.asarray(v[r], np.float32)
    # delete 64 (main) rows
    dead = np.arange(200, 264, dtype=np.int64)
    assert mut.delete(dead) == dead.size
    for j in dead:
        live.pop(int(j))
    # insert 64 brand-new ids
    new = np.arange(5000, 5064, dtype=np.int64)
    v = rows(new.size)
    mut.upsert(v, new)
    for r, j in enumerate(new):
        live[int(j)] = np.asarray(v[r], np.float32)
    # duplicate re-upsert (ids still packed in the delta → dedup rebuild)
    rep2 = np.arange(0, 32, dtype=np.int64)
    v = rows(rep2.size)
    mut.upsert(v, rep2)
    for r, j in enumerate(rep2):
        live[int(j)] = np.asarray(v[r], np.float32)
    # delete a few DELTA rows too (tombstone the write segment itself)
    dead2 = np.arange(5000, 5008, dtype=np.int64)
    assert mut.delete(dead2) == dead2.size
    for j in dead2:
        live.pop(int(j))
    return live


def _oracle(kind, dtype, world, live):
    """From-scratch rebuild of exactly the live rows."""
    bp = _params(kind)
    ids = np.array(sorted(live), dtype=np.int64)
    x = jnp.asarray(np.stack([live[int(j)] for j in ids]),
                    jnp.dtype(dtype))
    if world > 1:
        return _family(kind).build_sharded(bp, x, _comms(world),
                                           ids=jnp.asarray(ids, jnp.int32))
    return _family(kind).build(bp, x, ids=jnp.asarray(ids, jnp.int32))


def _search_oracle(kind, world, oracle, q, sp):
    if world > 1:
        return ann_mnmg.search(oracle, q, _K, sp)
    return _family(kind).search(sp, oracle, q, _K)


def _full_sp(kind):
    if kind == "ivf_flat":
        return ivf_flat.SearchParams(n_probes=_LISTS)
    return ivf_pq.SearchParams(n_probes=_LISTS)


def _assert_vs_oracle(kind, dtype, world, mut, live, seed=9):
    """The oracle comparison both before and after compaction."""
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.random((16, _DIM)).astype(np.float32),
                    jnp.dtype(dtype))
    sp = _full_sp(kind)
    d_m, i_m = mutable.search(mut, q, _K, params=sp)
    d_m, i_m = np.asarray(d_m, np.float64), np.asarray(i_m)
    # live-set discipline is UNCONDITIONAL: no dead id, ever
    assert set(i_m.ravel().tolist()) <= set(live), \
        "merged search returned a tombstoned/unknown id"
    if kind == "ivf_flat":
        # full probes remove probe selection: merged == rebuild oracle
        # bit-for-bit in distances, same id SET per row (tie ORDER at
        # duplicated distances is the one documented divergence)
        oracle = _oracle(kind, dtype, world, live)
        d_o, i_o = _search_oracle(kind, world, oracle, q, sp)
        d_o, i_o = np.asarray(d_o, np.float64), np.asarray(i_o)
        np.testing.assert_array_equal(d_m, d_o)
        for row_m, row_o in zip(i_m, i_o):
            assert set(row_m.tolist()) == set(row_o.tolist())
    else:
        # PQ: the oracle retrains its codebooks, so compare behaviorally —
        # a delta row queried BY ITS OWN VECTOR must surface (its code is
        # the exact encoding of the query)
        up_ids = [j for j in (list(range(32)) + list(range(5008, 5064)))
                  if j in live][:16]
        qd = jnp.asarray(np.stack([live[j] for j in up_ids]),
                         jnp.dtype(dtype))
        _, i_self = mutable.search(mut, qd, _K, params=sp)
        i_self = np.asarray(i_self)
        hits = sum(j in row.tolist() for j, row in zip(up_ids, i_self))
        assert hits >= int(0.8 * len(up_ids)), (
            f"only {hits}/{len(up_ids)} upserted rows retrieve "
            "themselves at full probes")


class TestChurnVsRebuildOracle:
    # tier-1 keeps the two f32 world-1 representatives (one per family —
    # the cells that carry the identity/oracle load); the bf16 and
    # world-2 cells are `slow` (tier-1 budget, ISSUE-20 rebalance):
    # world-2 stays covered by TestSnapshotRoundTrip[2], bf16 storage
    # rounding by the family recall tests, and the full grid runs in the
    # slow tier plus the BENCH_METRIC=mutable identity gate
    @pytest.mark.parametrize("kind,dtype,world", [
        ("ivf_flat", "float32", 1),
        ("ivf_pq", "float32", 1),
        pytest.param("ivf_flat", "float32", 2, marks=pytest.mark.slow),
        pytest.param("ivf_pq", "float32", 2, marks=pytest.mark.slow),
        pytest.param("ivf_flat", "bfloat16", 1, marks=pytest.mark.slow),
        pytest.param("ivf_pq", "bfloat16", 1, marks=pytest.mark.slow),
        pytest.param("ivf_flat", "bfloat16", 2, marks=pytest.mark.slow),
        pytest.param("ivf_pq", "bfloat16", 2, marks=pytest.mark.slow),
    ])
    def test_churn_then_compact_matches_oracle(self, kind, dtype, world):
        mut, live = _build_mut(kind, dtype, world)
        live = _churn(mut, live, dtype)
        assert mut.size == len(live)
        assert mut.delta_rows > 0 and mut.tombstone_count > 0
        _assert_vs_oracle(kind, dtype, world, mut, live)
        mut.compact()
        assert mut.delta_rows == 0 and mut.tombstone_count == 0
        assert mut.size == len(live)
        _assert_vs_oracle(kind, dtype, world, mut, live)


class TestWritePath:
    def test_warm_write_path_zero_compiles(self):
        """Steady-state writes never lower anything new: a delete is a
        bitmap value change, and an upsert whose resulting delta shapes
        were seen before replays warmed executables (counter-asserted —
        the tentpole's O(n_new) zero-compile write claim)."""
        mut, live = _build_mut("ivf_flat", "float32", 1)
        rng = np.random.default_rng(3)
        v = rng.random((64, _DIM)).astype(np.float32)
        ids = np.arange(300, 364, dtype=np.int64)
        q = rng.random((8, _DIM)).astype(np.float32)
        sp = ivf_flat.SearchParams(n_probes=4)
        mut.upsert(v, ids)                       # shapes first seen here
        mutable.search(mut, q, _K, params=sp)    # warm the read signature
        c0 = aot_compile_counters["compiles"]
        assert mut.delete(np.arange(400, 432, dtype=np.int64)) == 32
        # same ids + same rows → dedup rebuild lands on identical shapes
        mut.upsert(v, ids)
        d, i = mutable.search(mut, q, _K, params=sp)
        assert aot_compile_counters["compiles"] == c0, \
            "warm write path compiled"
        assert np.asarray(d).shape == (8, _K)
        dead = set(range(400, 432))
        assert not (set(np.asarray(i).ravel().tolist()) & dead)

    def test_upsert_duplicate_ids_in_batch_rejected(self):
        from raft_tpu.core.error import LogicError

        mut, _ = _build_mut("ivf_flat", "float32", 1)
        v = np.zeros((2, _DIM), np.float32)
        with pytest.raises(LogicError):
            mut.upsert(v, np.array([7, 7], dtype=np.int64))


class TestCompactor:
    def test_tick_deterministic_and_contained(self):
        mut, live = _build_mut("ivf_flat", "float32", 1)
        live = _churn(mut, live, "float32")
        comp = mutable.Compactor(mut, delta_fraction=0.05,
                                 tomb_fraction=0.05, seed=3)
        assert comp.due()
        assert comp.tick() is True
        assert comp.compactions == 1 and comp.errors == 0
        assert mut.delta_rows == 0 and mut.tombstone_count == 0
        # below threshold: tick is a no-op, deterministically
        assert comp.tick() is False
        assert comp.compactions == 1
        # error containment: an injected refresh fault is counted, the
        # old core keeps serving, and the NEXT tick retries clean
        from raft_tpu.serve import ServeEngine

        eng = ServeEngine(mut, _K,
                          params=ivf_flat.SearchParams(n_probes=4),
                          max_batch=8)
        eng.warmup()
        mut.upsert(np.zeros((160, _DIM), np.float32),
                   np.arange(6000, 6160, dtype=np.int64))
        comp2 = mutable.Compactor(mut, eng, delta_fraction=0.05,
                                  tomb_fraction=0.05, seed=3)
        with faults.plan("refresh:stage=pre_swap:raise"):
            assert comp2.tick() is False
        assert comp2.errors == 1
        # the CORE swap preceded the faulted engine promote, so the data
        # is compacted and serving (which reads the live core) continues
        assert mut.delta_rows == 0
        (r,) = eng.search([np.zeros((3, _DIM), np.float32)])
        assert np.asarray(r[1]).shape == (3, _K)
        # fresh churn re-arms the threshold; the retry promotes clean.
        # Re-upserting the SAME ids keeps the live count constant, so the
        # retry's rebuild + rewarm land on the shapes the faulted tick
        # already warmed (budget: cache hits instead of fresh lowers)
        mut.upsert(np.ones((160, _DIM), np.float32),
                   np.arange(6000, 6160, dtype=np.int64))
        assert comp2.tick() is True
        assert comp2.errors == 1 and comp2.compactions == 1


class TestServeConcurrentChurn:
    def test_concurrent_search_during_faulted_compaction(self):
        """Reads race writes, a compaction promotes mid-stream, an
        injected pre-swap refresh fault fires, and an id returned by an
        in-flight read is deleted under it — zero failed requests, and
        the dead id stays dead."""
        from raft_tpu.serve import ServeEngine

        mut, live = _build_mut("ivf_flat", "float32", 1)
        sp = ivf_flat.SearchParams(n_probes=4)
        eng = ServeEngine(mut, _K, params=sp, max_batch=8)
        eng.warmup()
        rng = np.random.default_rng(11)
        stop = threading.Event()
        errors, seen = [], []

        def reader():
            r = np.random.default_rng(12)
            while not stop.is_set():
                q = r.random((5, _DIM)).astype(np.float32)
                try:
                    (res,) = eng.search([q])
                    d, i = res
                    if np.asarray(i).shape != (5, _K):
                        errors.append(f"bad shape {np.asarray(i).shape}")
                    seen.append(np.asarray(i).copy())
                except Exception as exc:  # noqa: BLE001 — the gate
                    errors.append(repr(exc))

        t = threading.Thread(target=reader)
        t.start()
        try:
            mut.upsert(rng.random((96, _DIM)).astype(np.float32),
                       np.arange(7000, 7096, dtype=np.int64))
            # delete an id an in-flight read just returned
            for _ in range(200):
                if seen:
                    break
                stop.wait(0.05)
            assert seen, "reader made no progress"
            victim = int(np.asarray(seen[-1]).ravel()[0])
            mut.delete(np.array([victim], dtype=np.int64))
            # faulted swap: compact raises at the pre-swap crash window;
            # serving continues (the backend reads the already-promoted
            # core through the engine's OLD backend object)
            with faults.plan("refresh:stage=pre_swap:raise"):
                with pytest.raises(faults.InjectedFault):
                    mut.compact(engine=eng)
            # replace EXISTING rows: the live count stays constant, so
            # the clean compact rebuilds at the shapes the faulted one
            # already warmed (budget: cache hits instead of fresh lowers)
            mut.upsert(rng.random((32, _DIM)).astype(np.float32),
                       np.arange(7000, 7032, dtype=np.int64))
            mut.compact(engine=eng)        # clean promote
        finally:
            stop.set()
            t.join(30)
        assert not errors, errors[:5]
        assert eng.stats["refreshes"] >= 1
        # the deleted in-flight id must be gone at full probe coverage
        if victim in live:
            qv = live[victim][None, :]
            _, i = mutable.search(mut, qv, _K, params=_full_sp("ivf_flat"))
            assert victim not in np.asarray(i).ravel().tolist()


class TestSnapshotRoundTrip:
    @pytest.mark.parametrize("world", (1, 2))
    def test_save_load_triple_preserves_results(self, tmp_path, world):
        from raft_tpu.neighbors import serialize

        mut, live = _build_mut("ivf_flat", "float32", world)
        live = _churn(mut, live, "float32")
        rng = np.random.default_rng(21)
        q = rng.random((9, _DIM)).astype(np.float32)
        sp = _full_sp("ivf_flat")
        d0, i0 = mutable.search(mut, q, _K, params=sp)
        path = str(tmp_path / "mut_snapshot")
        serialize.save_sharded(path, mut)
        loaded = serialize.load_sharded(
            path, _comms(world) if world > 1 else None)
        assert isinstance(loaded, mutable.MutableIndex)
        assert loaded.size == mut.size
        assert loaded.delta_rows == mut.delta_rows
        # the snapshot persists LIVE delta rows only, so delta rows that
        # were tombstoned in-place are simply absent after restore (never
        # resurrected, never re-tombstoned) — an equivalent-but-cleaner
        # state; main tombstones round-trip exactly
        assert loaded.tombstone_count <= mut.tombstone_count
        d1, i1 = mutable.search(loaded, q, _K, params=sp)
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        # the restored triple keeps mutating: compaction still works
        loaded.compact()
        assert loaded.size == len(live)
