"""Native host runtime (C++ via ctypes) vs numpy fallbacks.

The native paths must agree exactly with the pure-Python implementations
they accelerate (the reference keeps both a device and a host path for the
same stages; here the invariant is native == numpy).
"""

import numpy as np
import pytest

from raft_tpu import native


requires_native = pytest.mark.skipif(
    not native.is_available(), reason="no C++ toolchain available")


@requires_native
def test_dendrogram_matches_scipy():
    from scipy.cluster.hierarchy import linkage

    rng = np.random.default_rng(0)
    x = rng.random((60, 4))
    ref = linkage(x, method="single")
    # feed our dendrogram builder the same sorted MST edge stream scipy
    # uses implicitly: get it from our own single_linkage pipeline
    from raft_tpu.cluster.single_linkage import build_sorted_mst

    src, dst, w = build_sorted_mst(x.astype(np.float32))
    children, deltas, sizes = native.agglomerative.build_dendrogram(
        np.array(src), np.array(dst), np.array(w))
    np.testing.assert_allclose(np.sort(deltas), np.sort(ref[:, 2]), atol=1e-4)
    np.testing.assert_array_equal(np.sort(sizes), np.sort(ref[:, 3].astype(np.int64)))


@requires_native
def test_flatten_matches_python():
    rng = np.random.default_rng(1)
    x = rng.random((80, 3)).astype(np.float32)
    from raft_tpu.cluster.single_linkage import (
        build_dendrogram_host,
        build_sorted_mst,
    )

    src, dst, w = build_sorted_mst(x)
    children, _, _ = build_dendrogram_host(src, dst, w)
    for k in (2, 5, 10):
        nat = native.agglomerative.extract_flattened_clusters(children, k, 80)
        # independent pure-python union-find oracle
        parent = np.arange(2 * 80 - 1)

        def find(a):
            while parent[a] != a:
                parent[a] = parent[parent[a]]
                a = parent[a]
            return a

        for i in range(80 - k):
            a, b = children[i]
            parent[find(a)] = 80 + i
            parent[find(b)] = 80 + i
        roots = np.array([find(i) for i in range(80)])
        _, py = np.unique(roots, return_inverse=True)
        np.testing.assert_array_equal(nat, py)
        assert len(np.unique(nat)) == k


@requires_native
def test_make_monotonic_native():
    labels = np.array([5, 5, 9, 2, 9, 2, 7], np.int32)
    out, k = native.make_monotonic_host(labels)
    np.testing.assert_array_equal(out, [1, 1, 3, 0, 3, 0, 2])
    assert k == 4


@requires_native
def test_coo_canonicalize_native():
    rows = np.array([2, 0, 2, 1, 0], np.int32)
    cols = np.array([1, 3, 1, 0, 3], np.int32)
    vals = np.array([1.0, 2.0, -1.0, 4.0, 1.0])
    r, c, v = native.coo_canonicalize_host(rows, cols, vals)
    # (2,1) sums to 0 and is dropped; (0,3) merges to 3.0
    np.testing.assert_array_equal(r, [0, 1])
    np.testing.assert_array_equal(c, [3, 0])
    np.testing.assert_allclose(v, [3.0, 4.0])


def test_single_linkage_uses_native_transparently():
    # end-to-end: whatever path is active, clustering blobs works
    rng = np.random.default_rng(2)
    a = rng.normal(0, 0.3, (40, 2))
    b = rng.normal(5, 0.3, (40, 2))
    x = np.vstack([a, b]).astype(np.float32)
    from raft_tpu.cluster import single_linkage

    out = single_linkage(x, n_clusters=2)
    labels = np.array(out.labels)
    assert len(np.unique(labels)) == 2
    assert len(np.unique(labels[:40])) == 1
    assert len(np.unique(labels[40:])) == 1


def test_from_triplets_canonicalizes():
    import scipy.sparse as sp

    from raft_tpu.sparse import from_triplets

    rows = np.array([3, 0, 3, 1, 0, 2], np.int32)
    cols = np.array([1, 2, 1, 0, 2, 2], np.int32)
    vals = np.array([1.5, 2.0, -1.5, 4.0, 1.0, 0.0], np.float64)
    csr = from_triplets(rows, cols, vals, (4, 4))
    ref = sp.coo_matrix((vals, (rows, cols)), shape=(4, 4)).tocsr()
    ref.sum_duplicates()
    ref.eliminate_zeros()
    got = sp.csr_matrix((np.array(csr.data), np.array(csr.indices),
                         np.array(csr.indptr)), shape=(4, 4))
    assert (got != ref).nnz == 0


def test_make_monotonic_native_path():
    from raft_tpu.label import make_monotonic

    labels = np.array([30, 10, 30, 20], np.int32)
    out = np.array(make_monotonic(labels))
    np.testing.assert_array_equal(out, [2, 0, 2, 1])


@requires_native
def test_native_csr_to_ell_matches_numpy():
    import scipy.sparse as sps

    rng = np.random.default_rng(8)
    g = sps.random(200, 500, density=0.05, format="csr", dtype=np.float32,
                   random_state=2)
    r = 8
    cols, vals, ovr, ovc, ovv = native.csr_to_ell_host(
        g.indptr.astype(np.int64), g.indices, g.data, r)
    # reconstruct and compare against scipy
    dense = np.zeros(g.shape, np.float32)
    rows = np.repeat(np.arange(g.shape[0]), r).reshape(200, r)
    mask = vals != 0
    dense[rows[mask], cols[mask]] = vals[mask]
    dense[ovr, ovc] = ovv
    np.testing.assert_allclose(dense, g.toarray(), rtol=1e-6)


# ---- ABI edge cases (r5): empty inputs, invariant-violating inputs,
# overflow accounting, dtype width coverage ----


@requires_native
def test_build_dendrogram_rejects_non_forest():
    """An edge stream with a cycle (re-merging already-joined roots)
    violates the sorted-MST invariant; the C side must return nonzero and
    the binding must raise rather than write garbage."""
    src = np.array([0, 1, 0], np.int32)
    dst = np.array([1, 2, 2], np.int32)   # 0-1, 1-2, then 0-2 closes a cycle
    w = np.array([0.1, 0.2, 0.3], np.float32)
    with pytest.raises(ValueError, match="forest"):
        native.agglomerative.build_dendrogram(src, dst, w)


@requires_native
def test_build_dendrogram_self_loop_is_cycle():
    src = np.array([0, 1], np.int32)
    dst = np.array([1, 1], np.int32)      # self-loop: ra == rb immediately
    w = np.array([0.1, 0.2], np.float32)
    with pytest.raises(ValueError, match="forest"):
        native.agglomerative.build_dendrogram(src, dst, w)


@requires_native
def test_build_dendrogram_single_edge():
    """Minimal forest: 2 points, 1 edge."""
    children, deltas, sizes = native.agglomerative.build_dendrogram(
        np.array([0], np.int32), np.array([1], np.int32),
        np.array([0.5], np.float32))
    np.testing.assert_array_equal(children, [[0, 1]])
    np.testing.assert_array_equal(sizes, [2])


@requires_native
def test_extract_flattened_bad_n_clusters():
    children, _, _ = native.agglomerative.build_dendrogram(
        np.array([0, 2], np.int32), np.array([1, 0], np.int32),
        np.array([0.1, 0.2], np.float32))
    for bad in (0, -1, 4):   # valid range is 1..n (= 3)
        with pytest.raises(ValueError, match="n_clusters"):
            native.agglomerative.extract_flattened_clusters(children, bad, 3)


@requires_native
def test_extract_flattened_boundary_n_clusters():
    """k=1 (all merged) and k=n (nothing merged) are legal boundaries."""
    children, _, _ = native.agglomerative.build_dendrogram(
        np.array([0, 2], np.int32), np.array([1, 0], np.int32),
        np.array([0.1, 0.2], np.float32))
    all_one = native.agglomerative.extract_flattened_clusters(children, 1, 3)
    np.testing.assert_array_equal(all_one, [0, 0, 0])
    singletons = native.agglomerative.extract_flattened_clusters(children, 3, 3)
    np.testing.assert_array_equal(np.sort(singletons), [0, 1, 2])


@requires_native
def test_make_monotonic_empty_and_single():
    out, k = native.make_monotonic_host(np.array([], np.int32))
    assert out.shape == (0,) and k == 0
    out, k = native.make_monotonic_host(np.array([42], np.int32))
    np.testing.assert_array_equal(out, [0])
    assert k == 1


@requires_native
def test_make_monotonic_negative_and_extreme_labels():
    """int32 extremes must not overflow the dense relabeling."""
    labels = np.array([2**31 - 1, -2**31, 0, 2**31 - 1], np.int32)
    out, k = native.make_monotonic_host(labels)
    np.testing.assert_array_equal(out, [2, 0, 1, 2])
    assert k == 3


@requires_native
def test_coo_canonicalize_empty():
    r, c, v = native.coo_canonicalize_host(
        np.array([], np.int32), np.array([], np.int32),
        np.array([], np.float64))
    assert r.shape == (0,) and c.shape == (0,) and v.shape == (0,)


@requires_native
def test_coo_canonicalize_all_cancel():
    """Every duplicate group sums to zero → empty canonical form."""
    rows = np.array([1, 1, 0, 0], np.int32)
    cols = np.array([2, 2, 3, 3], np.int32)
    vals = np.array([5.0, -5.0, 1.25, -1.25])
    r, c, v = native.coo_canonicalize_host(rows, cols, vals)
    assert r.shape == (0,)


@requires_native
def test_coo_canonicalize_keep_zeros():
    rows = np.array([1, 1], np.int32)
    cols = np.array([2, 2], np.int32)
    vals = np.array([5.0, -5.0])
    r, c, v = native.coo_canonicalize_host(rows, cols, vals,
                                           drop_zeros=False)
    np.testing.assert_array_equal(r, [1])
    np.testing.assert_allclose(v, [0.0])


@requires_native
def test_csr_to_ell_overflow_accounting_exact():
    """The overflow arrays must hold EXACTLY sum(max(nnz_row - r, 0))
    entries, in row order, with the in-row tail beyond r."""
    import scipy.sparse as sps

    indptr = np.array([0, 5, 5, 7], np.int64)       # rows: 5, 0, 2 nnz
    indices = np.array([0, 1, 2, 3, 4, 1, 2], np.int32)
    data = np.arange(7, dtype=np.float32) + 1
    r = 2
    cols, vals, ovr, ovc, ovv = native.csr_to_ell_host(indptr, indices,
                                                       data, r)
    assert ovr.shape == (3,)                        # row0 spills 5-2=3
    np.testing.assert_array_equal(ovr, [0, 0, 0])
    np.testing.assert_array_equal(ovc, [2, 3, 4])
    np.testing.assert_allclose(ovv, [3.0, 4.0, 5.0])
    np.testing.assert_array_equal(cols[0], [0, 1])
    np.testing.assert_array_equal(cols[1], [0, 0])  # empty row zero-padded
    np.testing.assert_allclose(vals[1], [0.0, 0.0])
    # reconstruct == original
    dense = np.zeros((3, 5), np.float32)
    for i in range(3):
        for j in range(r):
            if vals[i, j] != 0:
                dense[i, cols[i, j]] = vals[i, j]
    dense[ovr, ovc] = ovv
    ref = sps.csr_matrix((data, indices, indptr), shape=(3, 5)).toarray()
    np.testing.assert_allclose(dense, ref)


@requires_native
def test_csr_to_ell_malformed_indptr_raises():
    indptr = np.array([0, 3, 2, 4], np.int64)       # decreasing: e < s
    indices = np.zeros(4, np.int32)
    data = np.zeros(4, np.float32)
    with pytest.raises(ValueError, match="indptr"):
        native.csr_to_ell_host(indptr, indices, data, 2)


@requires_native
def test_csr_to_ell_empty_matrix():
    cols, vals, ovr, ovc, ovv = native.csr_to_ell_host(
        np.array([0], np.int64), np.array([], np.int32),
        np.array([], np.float32), 4)
    assert cols.shape == (0, 4) and ovr.shape == (0,)


@requires_native
def test_csr_to_ell_dtype_widths():
    """Bytewise value copy must be exact for 2-, 4- and 8-byte dtypes
    (one symbol serves every dtype via elem_size)."""
    import scipy.sparse as sps

    rng = np.random.default_rng(3)
    g64 = sps.random(50, 60, density=0.1, format="csr", dtype=np.float64,
                     random_state=4)
    for dtype in (np.float32, np.float64):
        g = g64.astype(dtype)
        r = 4
        cols, vals, ovr, ovc, ovv = native.csr_to_ell_host(
            g.indptr.astype(np.int64), g.indices, g.data, r)
        assert vals.dtype == dtype and ovv.dtype == dtype
        dense = np.zeros(g.shape, dtype)
        rows = np.repeat(np.arange(g.shape[0]), r).reshape(-1, r)
        mask = vals != 0
        dense[rows[mask], cols[mask]] = vals[mask]
        dense[ovr, ovc] = ovv
        np.testing.assert_array_equal(dense, g.toarray())
    # f16 (2-byte path) via hand-built CSR — scipy.sparse has no float16
    indptr = np.array([0, 3, 3, 5], np.int64)
    indices = np.array([4, 0, 2, 1, 3], np.int32)
    data = np.array([1.5, -2.25, 0.5, 3.0, 0.125], np.float16)
    cols, vals, ovr, ovc, ovv = native.csr_to_ell_host(indptr, indices,
                                                       data, 2)
    assert vals.dtype == np.float16 and ovv.dtype == np.float16
    np.testing.assert_array_equal(vals[0], data[:2])
    np.testing.assert_array_equal(ovv, data[2:3])       # row0 spills 1
    np.testing.assert_array_equal(vals[2], data[3:5])


@requires_native
def test_dendrogram_chain_vs_scipy_order():
    """A pathological chain (every merge extends one cluster) keeps exact
    scipy agreement — sizes must be 2, 3, ..., n."""
    from scipy.cluster.hierarchy import linkage

    n = 30
    x = np.arange(n, dtype=np.float32)[:, None] ** 1.1  # strictly spreading
    from raft_tpu.cluster.single_linkage import build_sorted_mst

    src, dst, w = build_sorted_mst(x)
    children, deltas, sizes = native.agglomerative.build_dendrogram(
        np.array(src), np.array(dst), np.array(w))
    ref = linkage(x.astype(np.float64), method="single")
    np.testing.assert_allclose(np.sort(deltas), np.sort(ref[:, 2]),
                               atol=1e-4)
    np.testing.assert_array_equal(sizes, np.arange(2, n + 1))
