"""Native host runtime (C++ via ctypes) vs numpy fallbacks.

The native paths must agree exactly with the pure-Python implementations
they accelerate (the reference keeps both a device and a host path for the
same stages; here the invariant is native == numpy).
"""

import numpy as np
import pytest

from raft_tpu import native


requires_native = pytest.mark.skipif(
    not native.is_available(), reason="no C++ toolchain available")


@requires_native
def test_dendrogram_matches_scipy():
    from scipy.cluster.hierarchy import linkage

    rng = np.random.default_rng(0)
    x = rng.random((60, 4))
    ref = linkage(x, method="single")
    # feed our dendrogram builder the same sorted MST edge stream scipy
    # uses implicitly: get it from our own single_linkage pipeline
    from raft_tpu.cluster.single_linkage import build_sorted_mst

    src, dst, w = build_sorted_mst(x.astype(np.float32))
    children, deltas, sizes = native.agglomerative.build_dendrogram(
        np.array(src), np.array(dst), np.array(w))
    np.testing.assert_allclose(np.sort(deltas), np.sort(ref[:, 2]), atol=1e-4)
    np.testing.assert_array_equal(np.sort(sizes), np.sort(ref[:, 3].astype(np.int64)))


@requires_native
def test_flatten_matches_python():
    rng = np.random.default_rng(1)
    x = rng.random((80, 3)).astype(np.float32)
    from raft_tpu.cluster.single_linkage import (
        build_dendrogram_host,
        build_sorted_mst,
    )

    src, dst, w = build_sorted_mst(x)
    children, _, _ = build_dendrogram_host(src, dst, w)
    for k in (2, 5, 10):
        nat = native.agglomerative.extract_flattened_clusters(children, k, 80)
        # independent pure-python union-find oracle
        parent = np.arange(2 * 80 - 1)

        def find(a):
            while parent[a] != a:
                parent[a] = parent[parent[a]]
                a = parent[a]
            return a

        for i in range(80 - k):
            a, b = children[i]
            parent[find(a)] = 80 + i
            parent[find(b)] = 80 + i
        roots = np.array([find(i) for i in range(80)])
        _, py = np.unique(roots, return_inverse=True)
        np.testing.assert_array_equal(nat, py)
        assert len(np.unique(nat)) == k


@requires_native
def test_make_monotonic_native():
    labels = np.array([5, 5, 9, 2, 9, 2, 7], np.int32)
    out, k = native.make_monotonic_host(labels)
    np.testing.assert_array_equal(out, [1, 1, 3, 0, 3, 0, 2])
    assert k == 4


@requires_native
def test_coo_canonicalize_native():
    rows = np.array([2, 0, 2, 1, 0], np.int32)
    cols = np.array([1, 3, 1, 0, 3], np.int32)
    vals = np.array([1.0, 2.0, -1.0, 4.0, 1.0])
    r, c, v = native.coo_canonicalize_host(rows, cols, vals)
    # (2,1) sums to 0 and is dropped; (0,3) merges to 3.0
    np.testing.assert_array_equal(r, [0, 1])
    np.testing.assert_array_equal(c, [3, 0])
    np.testing.assert_allclose(v, [3.0, 4.0])


def test_single_linkage_uses_native_transparently():
    # end-to-end: whatever path is active, clustering blobs works
    rng = np.random.default_rng(2)
    a = rng.normal(0, 0.3, (40, 2))
    b = rng.normal(5, 0.3, (40, 2))
    x = np.vstack([a, b]).astype(np.float32)
    from raft_tpu.cluster import single_linkage

    out = single_linkage(x, n_clusters=2)
    labels = np.array(out.labels)
    assert len(np.unique(labels)) == 2
    assert len(np.unique(labels[:40])) == 1
    assert len(np.unique(labels[40:])) == 1


def test_from_triplets_canonicalizes():
    import scipy.sparse as sp

    from raft_tpu.sparse import from_triplets

    rows = np.array([3, 0, 3, 1, 0, 2], np.int32)
    cols = np.array([1, 2, 1, 0, 2, 2], np.int32)
    vals = np.array([1.5, 2.0, -1.5, 4.0, 1.0, 0.0], np.float64)
    csr = from_triplets(rows, cols, vals, (4, 4))
    ref = sp.coo_matrix((vals, (rows, cols)), shape=(4, 4)).tocsr()
    ref.sum_duplicates()
    ref.eliminate_zeros()
    got = sp.csr_matrix((np.array(csr.data), np.array(csr.indices),
                         np.array(csr.indptr)), shape=(4, 4))
    assert (got != ref).nnz == 0


def test_make_monotonic_native_path():
    from raft_tpu.label import make_monotonic

    labels = np.array([30, 10, 30, 20], np.int32)
    out = np.array(make_monotonic(labels))
    np.testing.assert_array_equal(out, [2, 0, 2, 1])


@requires_native
def test_native_csr_to_ell_matches_numpy():
    import scipy.sparse as sps

    rng = np.random.default_rng(8)
    g = sps.random(200, 500, density=0.05, format="csr", dtype=np.float32,
                   random_state=2)
    r = 8
    cols, vals, ovr, ovc, ovv = native.csr_to_ell_host(
        g.indptr.astype(np.int64), g.indices, g.data, r)
    # reconstruct and compare against scipy
    dense = np.zeros(g.shape, np.float32)
    rows = np.repeat(np.arange(g.shape[0]), r).reshape(200, r)
    mask = vals != 0
    dense[rows[mask], cols[mask]] = vals[mask]
    dense[ovr, ovc] = ovv
    np.testing.assert_allclose(dense, g.toarray(), rtol=1e-6)
