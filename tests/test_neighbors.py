"""Brute-force kNN / merge-parts / eps-neighborhood / haversine vs oracles.

Oracle style mirrors reference test/neighbors/*: exact methods are checked
for exact agreement with a trivially-correct host computation.
"""

import numpy as np
import pytest
from scipy.spatial.distance import cdist

from raft_tpu.neighbors import (
    eps_neighbors_l2sq,
    fused_l2_knn,
    haversine_knn,
    knn,
    knn_merge_parts,
)


def ref_knn(index, queries, k, metric="euclidean", **kw):
    d = cdist(queries, index, metric, **kw)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=1), idx


@pytest.mark.parametrize("metric,scipy_metric", [
    ("euclidean", "euclidean"),
    ("sqeuclidean", "sqeuclidean"),
    ("cityblock", "cityblock"),
    ("cosine", "cosine"),
    ("chebyshev", "chebyshev"),
])
def test_knn_matches_scipy(metric, scipy_metric):
    rng = np.random.default_rng(0)
    index = rng.random((500, 16)).astype(np.float32)
    queries = rng.random((60, 16)).astype(np.float32)
    k = 10
    d, i = knn(index, queries, k, metric)
    rd, ri = ref_knn(index.astype(np.float64), queries.astype(np.float64), k,
                     scipy_metric)
    # distances must match; indices may differ only on ties
    np.testing.assert_allclose(np.array(d), rd, atol=1e-4)
    same = (np.array(i) == ri).mean()
    assert same > 0.99


def test_knn_tiling_invariance():
    rng = np.random.default_rng(1)
    index = rng.random((300, 8)).astype(np.float32)
    queries = rng.random((40, 8)).astype(np.float32)
    d1, i1 = knn(index, queries, 5)
    d2, i2 = knn(index, queries, 5, batch_size_index=64, batch_size_query=16)
    np.testing.assert_allclose(np.array(d1), np.array(d2), atol=1e-5)
    np.testing.assert_array_equal(np.array(i1), np.array(i2))


def test_fused_l2_knn():
    rng = np.random.default_rng(2)
    index = rng.random((200, 12)).astype(np.float32)
    queries = rng.random((30, 12)).astype(np.float32)
    d, i = fused_l2_knn(index, queries, 4, sqrt=True)
    rd, ri = ref_knn(index.astype(np.float64), queries.astype(np.float64), 4)
    np.testing.assert_allclose(np.array(d), rd, atol=1e-4)


def test_knn_merge_parts_equals_global():
    rng = np.random.default_rng(3)
    parts = [rng.random((150, 8)).astype(np.float32) for _ in range(3)]
    queries = rng.random((25, 8)).astype(np.float32)
    k = 7
    pd, pi = [], []
    for p in parts:
        d, i = knn(p, queries, k)
        pd.append(d)
        pi.append(i)
    offsets = np.cumsum([0] + [p.shape[0] for p in parts[:-1]])
    md, mi = knn_merge_parts(np.stack(pd), np.stack(pi), k,
                             translations=offsets.tolist())
    full = np.concatenate(parts, axis=0)
    fd, fi = knn(full, queries, k)
    np.testing.assert_allclose(np.array(md), np.array(fd), atol=1e-5)
    np.testing.assert_array_equal(np.array(mi), np.array(fi))


def test_eps_neighbors():
    rng = np.random.default_rng(4)
    x = rng.random((80, 5)).astype(np.float32)
    y = rng.random((120, 5)).astype(np.float32)
    eps_sq = 0.3
    adj, vd = eps_neighbors_l2sq(x, y, eps_sq, batch_size=32)
    ref = cdist(x, y, "sqeuclidean") <= eps_sq
    np.testing.assert_array_equal(np.array(adj), ref)
    np.testing.assert_array_equal(np.array(vd), ref.sum(1))


def test_haversine_knn():
    rng = np.random.default_rng(5)
    lat = rng.uniform(-np.pi / 2, np.pi / 2, 100)
    lon = rng.uniform(-np.pi, np.pi, 100)
    pts = np.stack([lat, lon], axis=1).astype(np.float32)
    q = pts[:10] + 0.01
    d, i = haversine_knn(pts, q, 3)

    def hav(a, b):
        dlat = a[:, None, 0] - b[None, :, 0]
        dlon = a[:, None, 1] - b[None, :, 1]
        h = (np.sin(dlat / 2) ** 2 +
             np.cos(a[:, None, 0]) * np.cos(b[None, :, 0]) *
             np.sin(dlon / 2) ** 2)
        return 2 * np.arcsin(np.sqrt(np.clip(h, 0, 1)))

    full = hav(q.astype(np.float64), pts.astype(np.float64))
    ridx = np.argsort(full, axis=1, kind="stable")[:, :3]
    rd = np.take_along_axis(full, ridx, axis=1)
    np.testing.assert_allclose(np.array(d), rd, atol=1e-4)
    # nearest neighbor of a barely-perturbed point is the point itself
    assert np.array_equal(np.array(i)[:, 0], np.arange(10))


class TestAnnDispatch:
    """Legacy approx_knn_* surface (reference spatial/knn/ann.cuh:41,70 +
    ann_common.h param structs)."""

    def _data(self):
        rng = np.random.default_rng(0)
        x = rng.normal(0, 1, (3000, 32)).astype(np.float32)
        q = x[:20] + 0.01 * rng.normal(0, 1, (20, 32)).astype(np.float32)
        return x, q

    def _recall_vs_exact(self, x, q, d, i, k):
        from raft_tpu.neighbors import knn

        _, ti = knn(x, q, k)
        ti = np.asarray(ti)
        i = np.asarray(i)
        return sum(len(set(a.tolist()) & set(b.tolist()))
                   for a, b in zip(i, ti)) / ti.size

    @pytest.mark.parametrize("params", [
        pytest.param("flat", id="ivf_flat"),
        pytest.param("pq", id="ivf_pq"),
        pytest.param("sq", id="ivf_sq8"),
    ])
    def test_build_search_dispatch(self, params):
        from raft_tpu.neighbors import ann

        x, q = self._data()
        p = {"flat": ann.IVFFlatParam(nlist=32, nprobe=16),
             "pq": ann.IVFPQParam(nlist=32, nprobe=8, M=8, n_bits=8),
             "sq": ann.IVFSQParam(nlist=32, nprobe=8)}[params]
        index = ann.approx_knn_build_index(p, x)
        d, i = ann.approx_knn_search(index, q, 5)
        assert d.shape == (20, 5) and i.shape == (20, 5)
        rec = self._recall_vs_exact(x, q, d, i, 5)
        # ivf_pq gate 0.5: INFORMATION-LIMITED, not a scoring bug.  This
        # config codes ISOTROPIC N(0,1) 32-dim rows at M=8 → ds=4 dims per
        # subquantizer, where the ADC-oracle test (test_ivf_pq.py
        # test_ivf_pq_adc_matches_reconstruction_oracle) proves the
        # pipeline ranks exactly like the reconstruction oracle and the
        # hoisted-ADC triage (PR 3) measured recall 0.53 IDENTICAL across
        # {hoisted, in-scan} × {f32, bf16} LUTs with exact-f32 build-time
        # list tables — LUT precision contributes nothing.  Raising
        # nprobe 8 → 32 (all lists) only reaches 0.62: the ~0.6 ceiling is
        # what 8 bytes of code per 32 isotropic dims can express (cf. the
        # bench.py ivf_pq docstring's isotropic-data measurement).
        gates = {"flat": 0.9, "pq": 0.5, "sq": 0.6}
        assert rec > gates[params], rec

    def test_sq_rejects_unmapped_quantizer(self):
        from raft_tpu.core.error import RaftError
        from raft_tpu.neighbors import ann

        x, _ = self._data()
        with pytest.raises(RaftError, match="no TPU storage mapping"):
            ann.approx_knn_build_index(
                ann.IVFSQParam(nlist=8, nprobe=2,
                               qtype=ann.QuantizerType.QT_6bit), x)

    def test_sq_rejects_inner_product(self):
        from raft_tpu.core.error import RaftError
        from raft_tpu.distance import DistanceType
        from raft_tpu.neighbors import ann

        x, _ = self._data()
        with pytest.raises(RaftError, match="L2Expanded"):
            ann.approx_knn_build_index(
                ann.IVFSQParam(nlist=8, nprobe=2), x,
                metric=DistanceType.InnerProduct)

    def test_sq_distances_in_data_scale(self):
        from raft_tpu.neighbors import ann, knn

        rng = np.random.default_rng(1)
        x = (50.0 + 40.0 * rng.random((2000, 16))).astype(np.float32)
        q = x[:8]
        index = ann.approx_knn_build_index(
            ann.IVFSQParam(nlist=16, nprobe=16), x)
        d, i = ann.approx_knn_search(index, q, 3)
        dref, _ = knn(x, q, 3, metric="sqeuclidean")
        # dominant quantization error is the cross term 2·Σ δ_i ε_i with
        # ε ~ U(±scale/2): a few percent of the distance, not scale² ~ 6×
        # (which is what an unscaled code-unit result would be off by)
        np.testing.assert_allclose(np.asarray(d), np.asarray(dref),
                                   rtol=0.05, atol=10.0)


class TestKnnEdgeGrid:
    """Edge-case grid for the brute-force family (reference
    cpp/test/neighbors/knn.cu + fused_l2_knn.cu parameter grids)."""

    def test_k_extremes(self):
        rng = np.random.default_rng(10)
        index = rng.random((50, 6)).astype(np.float32)
        queries = rng.random((8, 6)).astype(np.float32)
        d1, i1 = knn(index, queries, 1)
        assert d1.shape == (8, 1) and i1.shape == (8, 1)
        dn, in_ = knn(index, queries, 50)
        # k == n returns every index exactly once, in ascending distance
        for row_i, row_d in zip(np.asarray(in_), np.asarray(dn)):
            assert sorted(row_i.tolist()) == list(range(50))
            assert np.all(np.diff(row_d) >= -1e-6)

    def test_inner_product_descending(self):
        """InnerProduct is a similarity: results come back best-first
        (descending), mirroring the reference's faiss::MetricType
        handling."""
        from raft_tpu.distance import DistanceType

        rng = np.random.default_rng(11)
        index = rng.normal(0, 1, (120, 10)).astype(np.float32)
        queries = rng.normal(0, 1, (15, 10)).astype(np.float32)
        d, i = knn(index, queries, 6, DistanceType.InnerProduct)
        d = np.asarray(d)
        assert np.all(np.diff(d, axis=1) <= 1e-5)
        want = queries @ index.T
        np.testing.assert_allclose(d[:, 0], want.max(axis=1), atol=1e-4)

    def test_batch_boundary_off_by_one(self):
        """Index/query sizes one off a batch multiple — the classic tiled
        -scan boundary bug class."""
        rng = np.random.default_rng(12)
        index = rng.random((65, 4)).astype(np.float32)   # 64 + 1
        queries = rng.random((17, 4)).astype(np.float32)  # 16 + 1
        d1, i1 = knn(index, queries, 3, batch_size_index=64,
                     batch_size_query=16)
        d2, i2 = knn(index, queries, 3)
        # f32 accumulation order differs between tile configurations
        # (~1e-6 absolute); what must hold is that both pick the same
        # neighbors and agree on their distances to f32 tolerance
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   atol=2e-5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_fused_l2_knn_sqrt_flag(self):
        rng = np.random.default_rng(13)
        index = rng.random((90, 7)).astype(np.float32)
        queries = rng.random((11, 7)).astype(np.float32)
        d_sq, _ = fused_l2_knn(index, queries, 5, sqrt=False)
        d_rt, _ = fused_l2_knn(index, queries, 5, sqrt=True)
        np.testing.assert_allclose(np.sqrt(np.asarray(d_sq)),
                                   np.asarray(d_rt), atol=1e-5)

    def test_merge_parts_default_translations(self):
        """Without translations, part-local ids pass through unchanged
        (the reference's nullptr translations path)."""
        rng = np.random.default_rng(14)
        pd = np.sort(rng.random((2, 9, 4)), axis=2).astype(np.float32)
        pi = rng.integers(0, 100, (2, 9, 4)).astype(np.int32)
        md, mi = knn_merge_parts(pd, pi, 4)
        md, mi = np.asarray(md), np.asarray(mi)
        # merged distances are the global k smallest of the two parts
        want = np.sort(np.concatenate([pd[0], pd[1]], axis=1), axis=1)[:, :4]
        np.testing.assert_allclose(md, want, atol=1e-6)
        # every merged id must exist in the corresponding input rows
        for q in range(9):
            assert set(mi[q].tolist()) <= (set(pi[0, q].tolist())
                                           | set(pi[1, q].tolist()))

    def test_f64_index(self):
        rng = np.random.default_rng(15)
        index = rng.random((70, 5))
        queries = rng.random((9, 5))
        d, i = knn(index, queries, 4)
        rd, ri = ref_knn(index, queries, 4)
        np.testing.assert_allclose(np.asarray(d), rd, atol=1e-10)
        np.testing.assert_array_equal(np.asarray(i), ri)


def test_knn_bf16_inputs_f32_distances():
    """bf16 index/queries: distances come back f32 (pairwise accumulates
    half inputs in f32; the running top-k carry follows)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(20)
    x64, q64 = rng.random((300, 16)), rng.random((40, 16))
    d, i = knn(jnp.asarray(x64, jnp.bfloat16), jnp.asarray(q64, jnp.bfloat16),
               5, batch_size_index=128)
    assert d.dtype == jnp.float32
    ref = np.argsort(cdist(q64, x64), axis=1)[:, :5]
    assert (np.asarray(i) == ref).mean() > 0.9  # bf16 rounding flips ties


def test_ivf_k_exceeds_candidates_pads_with_sentinels():
    """k larger than the live candidate count returns (-1, +inf) padding
    after all real neighbours — the reference's empty-slot convention —
    for both IVF indexes and for under-probed searches."""
    from raft_tpu.neighbors import ivf_flat, ivf_pq

    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (20, 8)).astype(np.float32)
    q = rng.normal(0, 1, (3, 8)).astype(np.float32)

    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=4), x)
    d, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=4), idx, q, 30)
    i, d = np.asarray(i), np.asarray(d)
    assert i.shape == (3, 30)
    for row_i, row_d in zip(i, d):
        n_valid = (row_i >= 0).sum()
        assert n_valid == 20                      # every real row found
        assert (row_i[n_valid:] == -1).all()
        assert np.isinf(row_d[n_valid:]).all()
        assert (np.diff(row_d[:n_valid]) >= -1e-6).all()  # sorted prefix

    # under-probing: real results first, sentinels after
    d, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=1), idx, q, 10)
    i, d = np.asarray(i), np.asarray(d)
    for row_i, row_d in zip(i, d):
        n_valid = (row_i >= 0).sum()
        assert 0 < n_valid <= 10
        assert (row_i[n_valid:] == -1).all() and np.isinf(row_d[n_valid:]).all()

    pqi = ivf_pq.build(ivf_pq.IndexParams(n_lists=4, pq_dim=4, pq_bits=8), x)
    d, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=4), pqi, q, 30)
    i, d = np.asarray(i), np.asarray(d)
    for row_i, row_d in zip(i, d):                # full convention, as flat
        n_valid = (row_i >= 0).sum()
        assert n_valid == 20
        assert sorted(row_i[:n_valid].tolist()) == list(range(20))
        assert (row_i[n_valid:] == -1).all()
        assert np.isinf(row_d[n_valid:]).all()
        assert (np.diff(row_d[:n_valid]) >= -1e-6).all()


def test_ivf_duplicate_rows_all_retrievable():
    """An index of identical rows returns each id exactly once per query
    (ties must not drop or duplicate candidates)."""
    from raft_tpu.neighbors import ivf_flat

    x = np.zeros((10, 8), np.float32)
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8), x)
    q = 100.0 + np.zeros((2, 8), np.float32)
    d, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=8), idx, q, 10)
    i = np.asarray(i)
    for row in i:
        assert sorted(row.tolist()) == list(range(10))


def test_eps_neighbors_oracle_and_batching_invariance():
    """eps-neighborhood adjacency equals the dense oracle at any batch
    size, boundary points (distance exactly eps^2) follow one consistent
    convention, and vertex degrees match the adjacency row sums."""
    from scipy.spatial.distance import cdist

    from raft_tpu.neighbors import eps_neighbors_l2sq

    rng = np.random.default_rng(5)
    x = rng.normal(0, 1, (90, 8)).astype(np.float32)
    y = rng.normal(0, 1, (70, 8)).astype(np.float32)
    d2 = cdist(x.astype(np.float64), y.astype(np.float64),
               "sqeuclidean")
    eps_sq = float(np.quantile(d2, 0.1))
    ref = d2 < eps_sq
    outs = []
    for bs in (7, 32, 128):
        adj, vd = eps_neighbors_l2sq(x, y, eps_sq, batch_size=bs)
        adj, vd = np.asarray(adj), np.asarray(vd)
        outs.append(adj)
        np.testing.assert_array_equal(vd, adj.sum(1))
    # batching cannot change the adjacency
    np.testing.assert_array_equal(outs[0], outs[1])
    np.testing.assert_array_equal(outs[1], outs[2])
    # and it matches the oracle away from the eps^2 boundary (f32 ties at
    # the threshold may differ from the f64 oracle)
    margin = np.abs(d2 - eps_sq) > 1e-5
    np.testing.assert_array_equal(outs[0][margin], ref[margin])
