"""Graduated Pallas kernel engines (ISSUE 13, docs/pallas_kernels.md).

Interpret-mode oracles for the first-class kernel layer: the blockwise
``select_k`` must be BIT-IDENTICAL to the XLA engine (values AND
positions — the stability contract is pinned on crafted ties), the
``fused_l2_nn`` partials hook must reproduce the fused-EM carry, the
IVF-PQ LUT-in-VMEM scorer must match the hoisted-LUT scan within its
documented bounded error, and the engine-resolved search paths must
dispatch warm with ZERO compiles through the aot cache.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.kernels import select_k as pallas_select_k
from raft_tpu.kernels.engine import resolve_engine
from raft_tpu.matrix.select_k import select_k


# ------------------------------------------------------------- select_k


class TestSelectKBlockwise:
    @staticmethod
    def _adversarial(m, n, k, seed):
        """Random rows SEEDED WITH the hard cases: exact-tie pairs across
        column blocks, NaN entries, ±inf, and (row 1, when wide enough)
        fewer real entries than k — every grid cell stresses the tie /
        NaN preorder, not just bulk ordering."""
        rng = np.random.default_rng(seed)
        x = rng.normal(0, 1, (m, n)).astype(np.float32)
        if n > 40 and m >= 4:
            x[0, 5] = x[0, n - 7]            # exact tie across blocks
            x[1, : n - 2] = np.nan           # fewer than k real entries
            x[2, 9] = np.inf
            x[3, 11] = -np.inf
            x[m - 1, :3] = x[m - 1, 3]       # tie run inside one block
        return x

    # one CURATED grid covering the shape classes × dtypes × orientations
    # (a full cross product re-compiles an interpret network per cell —
    # tier-1 budget discipline, PR-3/PR-4 precedent); other tests in this
    # class REUSE these signatures so their aot executables are shared
    # tier-1 keeps two representatives (the shared-signature cell the
    # rest of the class reuses, the tiny-shape cell); the remaining
    # cells are `slow` (each interpret-network compile is ~16-22s cold —
    # ISSUE-14/ISSUE-19 budget rebalances, PR-3/PR-4 precedent; the
    # bf16 cells mostly exercise the documented sub-f32→f32 comparator
    # upcast, so f32 cells carry the network-correctness load)
    @pytest.mark.parametrize("m,n,k,select_min,dtype", [
        (7, 300, 10, True, np.float32),    # nothing aligned
        pytest.param(33, 1000, 1, True, np.float32,
                     marks=pytest.mark.slow),   # k=1, ragged rows
        pytest.param(64, 4096, 64, True, np.float32,
                     marks=pytest.mark.slow),   # filtered-path shape class
        pytest.param(16, 129, 100, False, np.float32,
                     marks=pytest.mark.slow),   # k near n, select_max
        (1, 17, 8, True, np.float32),      # single row, tiny n
        pytest.param(9, 700, 16, True, "bfloat16",
                     marks=pytest.mark.slow),   # bf16 comparator
        pytest.param(5, 257, 8, False, "bfloat16",
                     marks=pytest.mark.slow),   # bf16 select_max
    ])
    def test_bit_identical_to_xla_engine(self, dtype, select_min, m, n, k):
        x = jnp.asarray(self._adversarial(
            m, n, k, abs(hash((m, n, k, select_min))) % 2**31)
        ).astype(dtype)
        v_p, p_p = select_k(x, k, select_min=select_min, engine="pallas")
        v_x, p_x = select_k(x, k, select_min=select_min, engine="xla")
        np.testing.assert_array_equal(np.asarray(p_p), np.asarray(p_x))
        np.testing.assert_array_equal(
            np.asarray(v_p, np.float32), np.asarray(v_x, np.float32))

    def test_tie_stability_contract(self):
        """Duplicated values must resolve to the LOWEST positions first —
        the stable-lax.top_k contract merge_sorted_runs consumers rely
        on, reproduced by the kernel's lexicographic (value, position)
        order.  (Reuses the (7, 300, 10) grid signature — no fresh
        compile.)"""
        x = np.ones((7, 300), np.float32)
        x[:, 7] = 0.5
        x[:, 280] = 0.5           # tie pair across column blocks
        v_p, p_p = select_k(x, 10, engine="pallas")
        v_x, p_x = select_k(x, 10, engine="xla")
        np.testing.assert_array_equal(np.asarray(p_p), np.asarray(p_x))
        np.testing.assert_array_equal(np.asarray(p_p)[0, :3], [7, 280, 0])
        np.testing.assert_array_equal(np.asarray(v_p)[0, :2], [0.5, 0.5])

    # fresh (payload) signature → its own ~18s interpret compile; the
    # payload-gather path is exercised tier-1 through the IVF probe
    # scans (ISSUE-19 budget rebalance)
    @pytest.mark.slow
    def test_payload_indices_gathered(self):
        rng = np.random.default_rng(1)
        x = rng.normal(0, 1, (7, 300)).astype(np.float32)
        ids = rng.integers(0, 1 << 30, (7, 300)).astype(np.int32)
        v_p, i_p = select_k(x, 10, indices=ids, engine="pallas")
        v_x, i_x = select_k(x, 10, indices=ids, engine="xla")
        np.testing.assert_array_equal(np.asarray(i_p), np.asarray(i_x))
        np.testing.assert_array_equal(np.asarray(v_p), np.asarray(v_x))

    def test_unsupported_k_falls_back_to_xla(self):
        """k above the kernel cap silently keeps the XLA path — the
        engine knob is a preference, never a crash."""
        rng = np.random.default_rng(2)
        x = rng.normal(0, 1, (4, 2048)).astype(np.float32)
        k = pallas_select_k.MAX_K + 8
        v_p, p_p = select_k(x, k, engine="pallas")
        v_x, p_x = select_k(x, k, engine="xla")
        np.testing.assert_array_equal(np.asarray(p_p), np.asarray(p_x))

    def test_int_dtype_falls_back(self):
        x = np.random.default_rng(3).integers(0, 1000, (5, 64)
                                              ).astype(np.int32)
        v_p, p_p = select_k(x, 4, engine="pallas")
        v_x, p_x = select_k(x, 4, engine="xla")
        np.testing.assert_array_equal(np.asarray(p_p), np.asarray(p_x))

    def test_zero_compile_warm_dispatch(self):
        """Eager pallas-engine select_k dispatches the aot cache: a warm
        same-signature replay performs ZERO compiles.  (The (7, 300, 10)
        signature is warmed by the tests above.)"""
        from raft_tpu.core.aot import aot_compile_counters

        rng = np.random.default_rng(4)
        select_k(jnp.asarray(rng.normal(0, 1, (7, 300)).astype(np.float32)),
                 10, engine="pallas")           # warm (likely cache-hit)
        c0 = aot_compile_counters["compiles"]
        out = select_k(jnp.asarray(
            rng.normal(0, 1, (7, 300)).astype(np.float32)), 10,
            engine="pallas")
        jax.block_until_ready(out[0])
        assert aot_compile_counters["compiles"] == c0


# ------------------------------------------------------ engine resolution


class TestEngineResolution:
    def test_unknown_engine_rejected(self):
        with pytest.raises(ValueError, match="unknown engine"):
            resolve_engine("select_k", engine="cuda")

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="kernel kind"):
            resolve_engine("warp_sort")

    def test_l2nn_metric_family_enforced(self):
        from raft_tpu.distance.distance_types import DistanceType

        with pytest.raises(ValueError, match="L2 metric family"):
            resolve_engine("l2nn", metric=DistanceType.CosineExpanded,
                           engine="pallas")

    def test_env_default_off(self, monkeypatch):
        for var in ("RAFT_TPU_PALLAS_SELECT_K", "RAFT_TPU_PALLAS_PQ_LUT"):
            monkeypatch.delenv(var, raising=False)
        assert resolve_engine("select_k") == "xla"
        assert resolve_engine("pq_lut") == "xla"

    def test_env_force_enables_off_tpu(self, monkeypatch):
        """``force`` opts the interpret path in on ANY backend — the
        bench A/B + multichip-battery hook."""
        monkeypatch.setenv("RAFT_TPU_PALLAS_SELECT_K", "force")
        assert resolve_engine("select_k", dtype=jnp.float32) == "pallas"
        # dtype the kernel does not cover falls back silently
        assert resolve_engine("select_k", dtype=jnp.int32) == "xla"

    def test_env_1_requires_tpu_and_experimental(self, monkeypatch):
        """The r5 demotion gate in its new single home: '1' alone enables
        nothing off-TPU, and on TPU still needs the experimental flag."""
        monkeypatch.setenv("RAFT_TPU_PALLAS_SELECT_K", "1")
        monkeypatch.delenv("RAFT_TPU_PALLAS_EXPERIMENTAL", raising=False)
        assert resolve_engine("select_k", dtype=jnp.float32) == "xla"
        monkeypatch.setenv("RAFT_TPU_PALLAS_EXPERIMENTAL", "1")
        expected = "pallas" if jax.default_backend() == "tpu" else "xla"
        assert resolve_engine("select_k", dtype=jnp.float32) == expected

    def test_explicit_pallas_allowed_off_tpu(self):
        # interpret mode needs no experimental acknowledgement
        assert resolve_engine("select_k", engine="pallas") == "pallas"


# --------------------------------------------- fused_l2_nn partials hook


class TestFusedL2nnPartials:
    @pytest.mark.parametrize("weighted", [False, True])
    def test_partials_match_fused_em_carry(self, weighted):
        """The kernel's in-VMEM one-hot accumulation reproduces the XLA
        fused-EM scan's carry: labels EXACTLY, partials to accumulation
        tolerance (association order differs)."""
        from raft_tpu.cluster import fused_em_step
        from raft_tpu.kernels.fused_l2nn import fused_l2_nn_partials

        rng = np.random.default_rng(5)
        c = (3.0 * rng.normal(0, 1, (32, 24))).astype(np.float32)
        labels = rng.integers(0, 32, 513)
        x = (c[labels] + 0.05 * rng.normal(0, 1, (513, 24))
             ).astype(np.float32)
        w = rng.random(513).astype(np.float32) if weighted else None
        val, idx, sums, wsum, inertia = fused_l2_nn_partials(
            x, c, w, interpret=True)
        ref = fused_em_step(x, c, sample_weights=w, engine="xla",
                            precision="highest", return_labels=True)
        np.testing.assert_array_equal(np.asarray(idx),
                                      np.asarray(ref.labels))
        np.testing.assert_allclose(np.asarray(sums), np.asarray(ref.sums),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(wsum),
                                   np.asarray(ref.weights), rtol=1e-6)
        np.testing.assert_allclose(float(inertia), float(ref.inertia),
                                   rtol=1e-4)

    def test_fused_em_step_pallas_engine_single_pass(self):
        """The public wiring: engine='pallas' routes fused_em_step through
        the single-pass kernel (labels included) and agrees with the XLA
        engine."""
        from raft_tpu.cluster import fused_em_step

        rng = np.random.default_rng(6)
        c = (3.0 * rng.normal(0, 1, (16, 12))).astype(np.float32)
        labels = rng.integers(0, 16, 300)
        x = (c[labels] + 0.05 * rng.normal(0, 1, (300, 12))
             ).astype(np.float32)
        p = fused_em_step(x, c, engine="pallas", precision="highest",
                          return_labels=True)
        ref = fused_em_step(x, c, engine="xla", precision="highest",
                            return_labels=True)
        np.testing.assert_array_equal(np.asarray(p.labels),
                                      np.asarray(ref.labels))
        np.testing.assert_allclose(np.asarray(p.sums),
                                   np.asarray(ref.sums),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(float(p.inertia), float(ref.inertia),
                                   rtol=1e-4)


# ------------------------------------------------ probe scans end to end


class TestProbeScanEngines:
    # tiny indexes: the contracts here are ENGINE wiring properties
    # (identity, bounded error, zero-compile), not recall — small shapes
    # keep the interpret-network compiles inside the tier-1 budget
    def _data(self, seed=7, n=768, dim=16, nq=17):
        rng = np.random.default_rng(seed)
        return (rng.standard_normal((n, dim)).astype(np.float32),
                rng.standard_normal((nq, dim)).astype(np.float32))

    # `slow` since ISSUE-19: the same engine-threaded search identity is
    # re-proven by the multichip battery's select_k_sharded_matches_local
    # case, and the pq-side identity test below stays tier-1
    @pytest.mark.slow
    def test_ivf_flat_search_engine_identity(self, monkeypatch):
        """select_k bit-identity makes the WHOLE ivf_flat search (coarse
        select + probe-scan top-k + merge) bit-identical across
        engines."""
        from raft_tpu.neighbors import ivf_flat

        x, q = self._data()
        idx = ivf_flat.build(
            ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), x)
        sp = ivf_flat.SearchParams(n_probes=3)
        d0, i0 = map(np.asarray, ivf_flat.search(sp, idx, q, 5))
        monkeypatch.setenv("RAFT_TPU_PALLAS_SELECT_K", "force")
        d1, i1 = map(np.asarray, ivf_flat.search(sp, idx, q, 5))
        np.testing.assert_array_equal(i0, i1)
        np.testing.assert_array_equal(d0, d1)

    # fp8-pq5 is the tier-1 representative (quantized LUT + sub-byte
    # unpack, the distinctive kernel paths); the plain f32-pq8 cell is
    # `slow` (ISSUE-14 budget rebalance)
    @pytest.mark.parametrize("lut_dtype,pq_bits", [
        pytest.param("float32", 8, marks=pytest.mark.slow),
        ("float8_e4m3", 5)])
    def test_ivf_pq_vmem_kernel_matches_hoisted_scan(self, monkeypatch,
                                                     lut_dtype, pq_bits):
        """The LUT-in-VMEM kernel ≡ the hoisted-LUT scan top-k within the
        documented bounded error (association order of the one-hot dot):
        distances allclose, near-total id overlap."""
        from raft_tpu.neighbors import ivf_pq

        x, q = self._data(seed=8)
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=8, pq_dim=4, pq_bits=pq_bits,
                               kmeans_n_iters=4), x)
        sp = ivf_pq.SearchParams(n_probes=3, lut_dtype=lut_dtype)
        d0, i0 = map(np.asarray, ivf_pq.search(sp, idx, q, 5))
        monkeypatch.setenv("RAFT_TPU_PALLAS_PQ_LUT", "force")
        d1, i1 = map(np.asarray, ivf_pq.search(sp, idx, q, 5))
        np.testing.assert_allclose(d0, d1, rtol=1e-4, atol=1e-4)
        overlap = np.mean([len(set(i0[r]) & set(i1[r])) / i0.shape[1]
                           for r in range(i0.shape[0])])
        assert overlap >= 0.95, overlap

    @pytest.mark.slow  # tier-1 budget (ISSUE-20 rebalance; PR-19
    # serve-warms-pallas-variant precedent): the fp8-pq5 vmem-match cell
    # stays tier-1 and ci/checks.sh re-lowers the kernel interpret route
    # in the strict analysis gate every run
    def test_ivf_pq_warm_dispatch_zero_compile(self, monkeypatch):
        """The pallas-engine search signature pins into the aot cache like
        any other: a warm same-shape replay performs ZERO compiles."""
        from raft_tpu.core.aot import aot_compile_counters
        from raft_tpu.neighbors import ivf_pq

        x, q = self._data(seed=9)
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=8, pq_dim=4, pq_bits=8,
                               kmeans_n_iters=4), x)
        sp = ivf_pq.SearchParams(n_probes=3)
        monkeypatch.setenv("RAFT_TPU_PALLAS_PQ_LUT", "force")
        out = ivf_pq.search(sp, idx, q, 5)      # cold: compiles
        jax.block_until_ready(out[0])
        c0 = aot_compile_counters["compiles"]
        out = ivf_pq.search(sp, idx, q + 0.25, 5)
        jax.block_until_ready(out[0])
        assert aot_compile_counters["compiles"] == c0

    # `slow` since ISSUE-19 (~31s, the single heaviest tier-1 test):
    # warm-then-zero-compile with the pallas engine stays tier-1 via
    # test_ivf_pq_warm_dispatch_zero_compile above, and engine-resolved
    # serve warming is pinned by the xla-engine serve batteries
    @pytest.mark.slow
    def test_serve_engine_warms_pallas_variant(self, monkeypatch):
        """ServeEngine resolves the kernel engine at backend construction
        and warmup() pre-lowers the PALLAS variant per (bucket, dtype)
        signature — steady-state coalesced serving stays zero-compile and
        identical to the solo pallas path."""
        from raft_tpu.core.aot import aot_compile_counters
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu.serve import ServeEngine

        monkeypatch.setenv("RAFT_TPU_PALLAS_SELECT_K", "force")
        x, q = self._data(seed=10)
        idx = ivf_flat.build(
            ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), x)
        sp = ivf_flat.SearchParams(n_probes=3)
        eng = ServeEngine(idx, 5, sp, max_batch=16)
        assert eng._backend.engine == "pallas"
        eng.warmup(dtypes=(jnp.float32,))        # buckets 8, 16
        eng.search([q[:3]])                      # plumbing warm call
        c0 = aot_compile_counters["compiles"]
        outs = eng.search([q[:5], q[5:9]])
        assert aot_compile_counters["compiles"] == c0
        for qq, (dd, ii) in zip((q[:5], q[5:9]), outs):
            d_solo, i_solo = ivf_flat.search(sp, idx, qq, 5)
            np.testing.assert_array_equal(ii, np.asarray(i_solo))
            np.testing.assert_array_equal(dd, np.asarray(d_solo))


# -------------------------------------------------- legacy gate delegates


def test_legacy_gate_surfaces_delegate(monkeypatch):
    """The historical per-module gates survive as thin delegates over the
    one policy home — same answers, one env parser."""
    from raft_tpu.distance import pallas_fused_l2nn, pallas_kernels
    from raft_tpu.kernels.engine import env_enabled

    monkeypatch.setenv("RAFT_TPU_PALLAS_NN", "force")
    monkeypatch.setenv("RAFT_TPU_PALLAS", "force")
    assert pallas_fused_l2nn.is_enabled() == env_enabled("l2nn") is True
    assert pallas_kernels.is_enabled() is True
    assert not pallas_kernels.is_enabled(k=pallas_kernels._MAX_K + 1)
