"""Pallas VPU-engine pairwise kernel vs the jnp engine (interpret mode —
the CPU-CI analogue of the reference's naive-kernel oracles)."""

import numpy as np
import pytest
from scipy.spatial.distance import cdist

from raft_tpu.distance.pallas_kernels import pairwise_accumulate


@pytest.mark.parametrize("op,scipy_metric,finalize", [
    ("l1", "cityblock", None),
    ("l2", "sqeuclidean", None),
    ("linf", "chebyshev", None),
    ("canberra", "canberra", None),
])
def test_pallas_accumulate_matches_scipy(op, scipy_metric, finalize):
    rng = np.random.default_rng(0)
    x = rng.random((40, 19)).astype(np.float32)
    y = rng.random((70, 19)).astype(np.float32)
    out = np.array(pairwise_accumulate(x, y, op, interpret=True))
    ref = cdist(x.astype(np.float64), y.astype(np.float64), scipy_metric)
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_pallas_lp_and_hamming():
    rng = np.random.default_rng(1)
    x = rng.random((25, 10)).astype(np.float32)
    y = rng.random((30, 10)).astype(np.float32)
    out = np.array(pairwise_accumulate(x, y, "lp", p=3.0, interpret=True))
    ref = cdist(x.astype(np.float64), y.astype(np.float64), "minkowski", p=3.0)
    np.testing.assert_allclose(out ** (1.0 / 3.0), ref, atol=1e-4)
    xi = (rng.random((20, 12)) < 0.5).astype(np.float32)
    yi = (rng.random((22, 12)) < 0.5).astype(np.float32)
    out = np.array(pairwise_accumulate(xi, yi, "hamming", interpret=True))
    ref = cdist(xi, yi, "hamming") * 12  # accumulate = count, mean is epilogue
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_pallas_blocking_invariance():
    rng = np.random.default_rng(2)
    x = rng.random((150, 7)).astype(np.float32)
    y = rng.random((260, 7)).astype(np.float32)
    from raft_tpu.distance.pallas_kernels import _pairwise_pallas

    a = np.array(_pairwise_pallas(x, y, "l1", 2.0, bm=128, bn=128,
                                  interpret=True))
    b = np.array(_pairwise_pallas(x, y, "l1", 2.0, bm=32, bn=128,
                                  interpret=True))
    np.testing.assert_allclose(a, b, atol=1e-5)
