"""Pallas VPU-engine pairwise kernel vs the jnp engine (interpret mode —
the CPU-CI analogue of the reference's naive-kernel oracles)."""

import numpy as np
import pytest
from scipy.spatial.distance import cdist

from raft_tpu.distance.pallas_kernels import pairwise_accumulate


@pytest.mark.parametrize("op,scipy_metric,finalize", [
    ("l1", "cityblock", None),
    ("l2", "sqeuclidean", None),
    ("linf", "chebyshev", None),
    ("canberra", "canberra", None),
])
def test_pallas_accumulate_matches_scipy(op, scipy_metric, finalize):
    rng = np.random.default_rng(0)
    x = rng.random((40, 19)).astype(np.float32)
    y = rng.random((70, 19)).astype(np.float32)
    out = np.array(pairwise_accumulate(x, y, op, interpret=True))
    ref = cdist(x.astype(np.float64), y.astype(np.float64), scipy_metric)
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_pallas_lp_and_hamming():
    rng = np.random.default_rng(1)
    x = rng.random((25, 10)).astype(np.float32)
    y = rng.random((30, 10)).astype(np.float32)
    out = np.array(pairwise_accumulate(x, y, "lp", p=3.0, interpret=True))
    ref = cdist(x.astype(np.float64), y.astype(np.float64), "minkowski", p=3.0)
    np.testing.assert_allclose(out ** (1.0 / 3.0), ref, atol=1e-4)
    xi = (rng.random((20, 12)) < 0.5).astype(np.float32)
    yi = (rng.random((22, 12)) < 0.5).astype(np.float32)
    out = np.array(pairwise_accumulate(xi, yi, "hamming", interpret=True))
    ref = cdist(xi, yi, "hamming") * 12  # accumulate = count, mean is epilogue
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_pallas_blocking_invariance():
    rng = np.random.default_rng(2)
    x = rng.random((150, 7)).astype(np.float32)
    y = rng.random((260, 7)).astype(np.float32)
    from raft_tpu.distance.pallas_kernels import _pairwise_pallas

    a = np.array(_pairwise_pallas(x, y, "l1", 2.0, bm=128, bn=128,
                                  interpret=True))
    b = np.array(_pairwise_pallas(x, y, "l1", 2.0, bm=32, bn=128,
                                  interpret=True))
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_fused_l2_nn_pallas_matches_jnp():
    """Pallas fused distance+argmin (interpret mode) must agree with the
    jnp engine on values, indices, and tie-breaking."""
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn
    from raft_tpu.distance.pallas_fused_l2nn import fused_l2_nn_pallas

    rng = np.random.default_rng(3)
    x = rng.normal(0, 1, (513, 40)).astype(np.float32)   # non-multiples
    y = rng.normal(0, 1, (300, 40)).astype(np.float32)
    y[7] = y[211]                                        # exact tie pair
    val, idx = fused_l2_nn_pallas(x, y, bm=128, bn=128, bf16_dot=False,
                                  interpret=True)
    ref = fused_l2_nn(x, y, sqrt=False, precision="highest")
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ref.key))
    np.testing.assert_allclose(np.asarray(val), np.asarray(ref.value),
                               atol=1e-3)


def test_min_cluster_and_distance_pallas_engine():
    """engine="pallas" routes the k-means E-step through the fused kernel
    with identical assignments (interpret mode auto-selected off-TPU)."""
    import jax.numpy as jnp

    from raft_tpu.cluster import min_cluster_and_distance

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(0, 1, (400, 24)).astype(np.float32))
    c = jnp.asarray(rng.normal(0, 1, (32, 24)).astype(np.float32))
    base = min_cluster_and_distance(x, c, precision="highest")
    out = min_cluster_and_distance(x, c, precision="highest",
                                   engine="pallas")
    np.testing.assert_array_equal(np.asarray(out.key), np.asarray(base.key))
    np.testing.assert_allclose(np.asarray(out.value), np.asarray(base.value),
                               atol=1e-3)
