"""Pallas kernel validation (interpret mode — the CPU-CI analogue of the
reference's naive-kernel oracles, pairwise_distance_base.cuh tests).

These kernels are r5 scaffolds (they failed to compile on the only real
TPU path exercised — BENCH_TPU.md r4b), which makes their interpret-mode
contracts the ONLY continuously-verified property: the grids here cover
every op × blocking × shape class, the epilogue contracts the callers
rely on, tie-breaking, padding neutrality, and the experimental gating.
"""

import numpy as np
import pytest
from scipy.spatial.distance import cdist

from raft_tpu.distance.pallas_kernels import (
    _MAX_K,
    _OPS,
    _pairwise_pallas,
    pairwise_accumulate,
)

_SCIPY = {
    "l1": "cityblock",
    "l2": "sqeuclidean",
    "linf": "chebyshev",
    "canberra": "canberra",
}


# ---------------------------------------------------------------- op grids


@pytest.mark.parametrize("op,scipy_metric", sorted(_SCIPY.items()))
@pytest.mark.parametrize("m,n,k", [
    (40, 70, 19),    # nothing aligned
    (1, 1, 1),       # degenerate single pair
    (129, 5, 33),    # tall x, tiny y (row-pad + col-pad together)
    (3, 260, 8),     # tiny x, wide y (forces multiple col blocks)
])
def test_pallas_accumulate_matches_scipy(op, scipy_metric, m, n, k):
    rng = np.random.default_rng(abs(hash((op, m, n, k))) % 2**31)
    x = rng.random((m, k)).astype(np.float32)
    y = rng.random((n, k)).astype(np.float32)
    out = np.array(pairwise_accumulate(x, y, op, interpret=True))
    ref = cdist(x.astype(np.float64), y.astype(np.float64), scipy_metric)
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_pallas_ops_table_is_fully_covered():
    """Every op in the kernel's dispatch table has a grid or contract test
    in this file — a new op without an oracle fails here."""
    assert set(_OPS) == {"l1", "l2", "linf", "lp", "hamming", "canberra"}


@pytest.mark.parametrize("p", [0.5, 1.5, 3.0, 4.0])
def test_pallas_lp_epilogue_contract(p):
    """The kernel returns the RAW power sum; the caller's ^(1/p) epilogue
    (pairwise.py fin_op split) must reproduce Minkowski for any p."""
    rng = np.random.default_rng(1)
    x = rng.random((25, 10)).astype(np.float32)
    y = rng.random((30, 10)).astype(np.float32)
    out = np.array(pairwise_accumulate(x, y, "lp", p=p, interpret=True))
    ref = cdist(x.astype(np.float64), y.astype(np.float64), "minkowski", p=p)
    np.testing.assert_allclose(out ** (1.0 / p), ref, atol=1e-3)


def test_pallas_hamming_epilogue_contract():
    """The kernel accumulates the mismatch COUNT; /k is the caller's
    epilogue (reference hamming fin_op)."""
    rng = np.random.default_rng(2)
    k = 12
    xi = (rng.random((20, k)) < 0.5).astype(np.float32)
    yi = (rng.random((22, k)) < 0.5).astype(np.float32)
    out = np.array(pairwise_accumulate(xi, yi, "hamming", interpret=True))
    ref = cdist(xi, yi, "hamming")
    np.testing.assert_allclose(out / k, ref, atol=1e-5)
    # count-valued output is integral
    np.testing.assert_allclose(out, np.round(out), atol=1e-6)


def test_pallas_l2_sqrt_epilogue_contract():
    """sqrt of the accumulated unexpanded L2 == euclidean (the L2Sqrt
    epilogue the dispatcher fuses outside the kernel)."""
    rng = np.random.default_rng(3)
    x = rng.random((31, 9)).astype(np.float32)
    y = rng.random((17, 9)).astype(np.float32)
    out = np.array(pairwise_accumulate(x, y, "l2", interpret=True))
    ref = cdist(x.astype(np.float64), y.astype(np.float64), "euclidean")
    np.testing.assert_allclose(np.sqrt(out), ref, atol=1e-4)


def test_pallas_canberra_zero_coordinate_convention():
    """0/0 coordinates contribute 0 (reference canberra guard) — the
    padding-neutrality property the kernel's no-mask design relies on."""
    x = np.array([[0.0, 1.0, 0.0], [0.0, 0.0, 0.0]], np.float32)
    y = np.array([[0.0, 0.0, 2.0], [0.0, 3.0, 0.0]], np.float32)
    out = np.array(pairwise_accumulate(x, y, "canberra", interpret=True))
    ref = cdist(x.astype(np.float64), y.astype(np.float64), "canberra")
    np.testing.assert_allclose(out, ref, atol=1e-6)


# ----------------------------------------------------- blocking invariance


@pytest.mark.parametrize("op", sorted(_OPS))
@pytest.mark.parametrize("bm,bn", [(32, 128), (8, 256)])
def test_pallas_blocking_invariance(op, bm, bn):
    """Results are independent of the (bm, bn) tiling for every op — the
    grid revisit/merge logic cannot leak tile boundaries."""
    rng = np.random.default_rng(4)
    x = rng.random((150, 7)).astype(np.float32)
    y = rng.random((260, 7)).astype(np.float32)
    if op == "hamming":
        x = (x < 0.5).astype(np.float32)
        y = (y < 0.5).astype(np.float32)
    ref = np.array(_pairwise_pallas(x, y, op, 3.0, bm=128, bn=128,
                                    interpret=True))
    out = np.array(_pairwise_pallas(x, y, op, 3.0, bm=bm, bn=bn,
                                    interpret=True))
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_pallas_output_dtype_follows_input():
    rng = np.random.default_rng(5)
    x = rng.random((12, 6)).astype(np.float32)
    y = rng.random((9, 6)).astype(np.float32)
    out = pairwise_accumulate(x, y, "l1", interpret=True)
    assert out.dtype == np.float32
    assert out.shape == (12, 9)


# --------------------------------------------------------- fused L2 NN


def _fused_ref(x, y):
    d = cdist(x.astype(np.float64), y.astype(np.float64), "sqeuclidean")
    return d.min(axis=1), d.argmin(axis=1)


def test_fused_l2_nn_pallas_matches_jnp():
    """Pallas fused distance+argmin (interpret mode) must agree with the
    jnp engine on values, indices, and tie-breaking."""
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn
    from raft_tpu.distance.pallas_fused_l2nn import fused_l2_nn_pallas

    rng = np.random.default_rng(3)
    x = rng.normal(0, 1, (513, 40)).astype(np.float32)   # non-multiples
    y = rng.normal(0, 1, (300, 40)).astype(np.float32)
    y[7] = y[211]                                        # exact tie pair
    val, idx = fused_l2_nn_pallas(x, y, bm=128, bn=128, bf16_dot=False,
                                  interpret=True)
    ref = fused_l2_nn(x, y, sqrt=False, precision="highest")
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ref.key))
    np.testing.assert_allclose(np.asarray(val), np.asarray(ref.value),
                               atol=1e-3)


@pytest.mark.parametrize("m,k,bm,bn", [
    (64, 5, 64, 512),     # fewer centroids than one block
    (100, 3, 32, 1),      # single-centroid blocks exercise the j-merge
    (7, 33, 256, 512),    # everything smaller than the blocks
    (257, 129, 64, 64),   # multi-block on both grid axes
])
def test_fused_l2_nn_pallas_shape_grid(m, k, bm, bn):
    """Cross-block running-min merge is exact for every grid shape class
    (the revisited-output merge is the part the reference does with
    atomics, fused_l2_nn.cuh:132 — wrong merges show up as off-by-one
    block indices)."""
    from raft_tpu.distance.pallas_fused_l2nn import fused_l2_nn_pallas

    rng = np.random.default_rng(m * 1000 + k)
    x = rng.normal(0, 1, (m, 16)).astype(np.float32)
    y = rng.normal(0, 1, (k, 16)).astype(np.float32)
    val, idx = fused_l2_nn_pallas(x, y, bm=bm, bn=bn, bf16_dot=False,
                                  interpret=True)
    rv, ri = _fused_ref(x, y)
    np.testing.assert_array_equal(np.asarray(idx), ri)
    np.testing.assert_allclose(np.asarray(val), rv, atol=1e-3)


def test_fused_l2_nn_pallas_first_block_wins_ties_across_blocks():
    """A centroid duplicated across different COLUMN BLOCKS must resolve
    to the lower index (strict < merge): the cross-block analogue of the
    jnp argmin's first-wins rule."""
    from raft_tpu.distance.pallas_fused_l2nn import fused_l2_nn_pallas

    rng = np.random.default_rng(8)
    y = rng.normal(0, 1, (64, 8)).astype(np.float32)
    y[60] = y[3]                       # duplicates land in different blocks
    x = np.repeat(y[3][None, :], 5, 0).astype(np.float32)
    val, idx = fused_l2_nn_pallas(x, y, bm=8, bn=16,
                                  bf16_dot=False, interpret=True)
    np.testing.assert_array_equal(np.asarray(idx), 3)
    np.testing.assert_allclose(np.asarray(val), 0.0, atol=1e-5)


def test_fused_l2_nn_pallas_self_match():
    """Querying the centroid set against itself: every row's NN is itself
    at distance ~0 (catches any off-by-one in the block index offset)."""
    from raft_tpu.distance.pallas_fused_l2nn import fused_l2_nn_pallas

    rng = np.random.default_rng(9)
    y = rng.normal(0, 3, (90, 12)).astype(np.float32)
    val, idx = fused_l2_nn_pallas(y, y, bm=32, bn=32, bf16_dot=False,
                                  interpret=True)
    np.testing.assert_array_equal(np.asarray(idx), np.arange(90))
    np.testing.assert_allclose(np.asarray(val), 0.0, atol=1e-4)


def test_fused_l2_nn_pallas_bf16_dot_on_separated_data():
    """bf16_dot=True keeps exact argmins when clusters are separated well
    beyond bf16 rounding (the precision="default" contract the k-means
    wiring maps it to)."""
    from raft_tpu.distance.pallas_fused_l2nn import fused_l2_nn_pallas

    rng = np.random.default_rng(10)
    y = (10.0 * rng.normal(0, 1, (32, 16))).astype(np.float32)
    labels = rng.integers(0, 32, 200)
    x = (y[labels] + 0.01 * rng.normal(0, 1, (200, 16))).astype(np.float32)
    _, idx = fused_l2_nn_pallas(x, y, bm=64, bn=16, bf16_dot=True,
                                interpret=True)
    np.testing.assert_array_equal(np.asarray(idx), labels)


def test_fused_l2_nn_pallas_d_cap():
    from raft_tpu.distance.pallas_fused_l2nn import (_MAX_D,
                                                     fused_l2_nn_pallas)

    x = np.zeros((4, _MAX_D + 1), np.float32)
    with pytest.raises(ValueError, match="fused_l2_nn_pallas"):
        fused_l2_nn_pallas(x, x, interpret=True)


# ------------------------------------------------- engine wiring + gating


def test_min_cluster_and_distance_pallas_engine():
    """engine="pallas" routes the k-means E-step through the fused kernel
    with identical assignments (interpret mode auto-selected off-TPU)."""
    import jax.numpy as jnp

    from raft_tpu.cluster import min_cluster_and_distance

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(0, 1, (400, 24)).astype(np.float32))
    c = jnp.asarray(rng.normal(0, 1, (32, 24)).astype(np.float32))
    base = min_cluster_and_distance(x, c, precision="highest")
    out = min_cluster_and_distance(x, c, precision="highest",
                                   engine="pallas")
    np.testing.assert_array_equal(np.asarray(out.key), np.asarray(base.key))
    np.testing.assert_allclose(np.asarray(out.value), np.asarray(base.value),
                               atol=1e-3)


def test_pallas_engine_value_dtype_is_accum_dtype():
    """Half-precision data through the pallas engine still yields f32
    distances (the while_loop inertia carry contract)."""
    import jax.numpy as jnp

    from raft_tpu.cluster import min_cluster_and_distance

    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(0, 1, (64, 16)), jnp.bfloat16)
    c = jnp.asarray(rng.normal(0, 1, (8, 16)), jnp.bfloat16)
    out = min_cluster_and_distance(x, c, engine="pallas")
    assert out.value.dtype == jnp.float32


def test_pallas_is_enabled_requires_experimental_flag(monkeypatch):
    """r5 demotion: the env opt-ins alone may NOT enable either kernel —
    the experimental flag is the explicit acknowledgement of the known
    TPU compile failure (BENCH_TPU.md r4b)."""
    from raft_tpu.distance import pallas_fused_l2nn, pallas_kernels

    monkeypatch.setenv("RAFT_TPU_PALLAS_NN", "1")
    monkeypatch.setenv("RAFT_TPU_PALLAS", "1")
    monkeypatch.delenv("RAFT_TPU_PALLAS_EXPERIMENTAL", raising=False)
    assert not pallas_fused_l2nn.is_enabled()
    assert not pallas_kernels.is_enabled()
    # with the flag, the remaining gate is the backend (False on CPU CI)
    monkeypatch.setenv("RAFT_TPU_PALLAS_EXPERIMENTAL", "1")
    import jax

    expected = jax.default_backend() == "tpu"
    assert pallas_fused_l2nn.is_enabled() == expected
    assert pallas_kernels.is_enabled() == expected


def test_pallas_kernels_max_k_gate(monkeypatch):
    from raft_tpu.distance import pallas_kernels

    monkeypatch.setenv("RAFT_TPU_PALLAS", "1")
    monkeypatch.setenv("RAFT_TPU_PALLAS_EXPERIMENTAL", "1")
    assert not pallas_kernels.is_enabled(k=_MAX_K + 1)
