"""RNG + generator tests — counterpart of reference cpp/test/random/*
(moment-matching oracles, as in test/random/rng.cu)."""

import numpy as np
import pytest


from raft_tpu import random as rrandom
from raft_tpu.random import RngState


def test_rng_state_reproducible():
    a = rrandom.uniform(RngState(123), (1000,))
    b = rrandom.uniform(RngState(123), (1000,))
    c = rrandom.uniform(RngState(124), (1000,))
    np.testing.assert_array_equal(a, b)
    assert not np.allclose(a, c)


def test_rng_state_advances():
    st = RngState(5)
    a = rrandom.uniform(st, (100,))
    b = rrandom.uniform(st, (100,))
    assert not np.allclose(a, b)
    assert st.base_subsequence == 2


@pytest.mark.parametrize(
    "fn,kwargs,mean,std,tol",
    [
        (rrandom.uniform, dict(low=-1.0, high=3.0), 1.0, 4 / np.sqrt(12), 0.1),
        (rrandom.normal, dict(mu=2.0, sigma=0.5), 2.0, 0.5, 0.05),
        (rrandom.lognormal, dict(mu=0.0, sigma=0.25), np.exp(0.03125), None, 0.05),
        (rrandom.gumbel, dict(mu=0.0, beta=1.0), 0.5772, None, 0.05),
        (rrandom.logistic, dict(mu=1.0, scale=0.5), 1.0, None, 0.05),
        (rrandom.exponential, dict(lambda_=2.0), 0.5, None, 0.05),
        (rrandom.laplace, dict(mu=0.0, scale=1.0), 0.0, None, 0.1),
        (rrandom.rayleigh, dict(sigma=1.0), np.sqrt(np.pi / 2), None, 0.05),
    ],
)
def test_distribution_moments(fn, kwargs, mean, std, tol):
    x = np.asarray(fn(RngState(0), (40000,), **kwargs))
    assert abs(x.mean() - mean) < tol, f"{fn.__name__} mean {x.mean()} != {mean}"
    if std is not None:
        assert abs(x.std() - std) < tol


def test_uniform_int():
    x = np.asarray(rrandom.uniform_int(RngState(1), (10000,), 3, 9))
    assert x.min() == 3 and x.max() == 8


def test_bernoulli():
    x = np.asarray(rrandom.bernoulli(RngState(2), (20000,), prob=0.3))
    assert abs(x.mean() - 0.3) < 0.02
    y = np.asarray(rrandom.scaled_bernoulli(RngState(2), (1000,), prob=0.5, scale=2.0))
    assert set(np.unique(y)) == {-2.0, 2.0}


def test_normal_table():
    mu = np.array([0.0, 10.0, -5.0], np.float32)
    sig = np.array([1.0, 0.1, 2.0], np.float32)
    x = np.asarray(rrandom.normal_table(RngState(3), 20000, mu, sig))
    np.testing.assert_allclose(x.mean(axis=0), mu, atol=0.1)
    np.testing.assert_allclose(x.std(axis=0), sig, atol=0.1)


def test_discrete():
    w = np.array([0.1, 0.0, 0.6, 0.3])
    x = np.asarray(rrandom.discrete(RngState(4), (30000,), w))
    counts = np.bincount(x, minlength=4) / 30000
    np.testing.assert_allclose(counts, w, atol=0.02)
    assert counts[1] == 0


def test_sample_without_replacement():
    items = np.arange(100)
    out, idx = rrandom.sample_without_replacement(
        RngState(5), items, 30, return_indices=True
    )
    assert len(set(np.asarray(idx).tolist())) == 30  # no duplicates
    np.testing.assert_array_equal(np.asarray(out), items[np.asarray(idx)])
    # Heavily weighted item should essentially always be included
    w = np.ones(100)
    w[17] = 1e6
    hits = 0
    for seed in range(20):
        out = rrandom.sample_without_replacement(RngState(seed), items, 5, weights=w)
        hits += 17 in np.asarray(out)
    assert hits == 20


def test_permute():
    x = np.arange(50, dtype=np.float32).reshape(50, 1)
    out, perm = rrandom.permute(RngState(6), x)
    assert sorted(np.asarray(perm).tolist()) == list(range(50))
    np.testing.assert_allclose(np.asarray(out)[:, 0], np.asarray(perm))


def test_make_blobs():
    x, labels, centers = rrandom.make_blobs(
        RngState(7), 600, 8, n_clusters=4, cluster_std=0.1
    )
    assert x.shape == (600, 8) and labels.shape == (600,)
    # every point lies near its assigned center
    d = np.linalg.norm(np.asarray(x) - np.asarray(centers)[np.asarray(labels)], axis=1)
    assert d.max() < 2.0
    # roughly balanced clusters
    counts = np.bincount(np.asarray(labels), minlength=4)
    assert counts.min() >= 140


def test_make_regression():
    x, y, w = rrandom.make_regression(
        RngState(8), 200, 10, n_informative=5, noise=0.0, coef=True, shuffle=False
    )
    np.testing.assert_allclose(np.asarray(x) @ np.asarray(w), np.asarray(y), rtol=1e-3)
    assert np.allclose(np.asarray(w)[5:], 0)


def test_multi_variable_gaussian():
    mean = np.array([1.0, -2.0])
    cov = np.array([[2.0, 0.6], [0.6, 1.0]])
    x = np.asarray(rrandom.multi_variable_gaussian(RngState(9), mean, cov, 50000))
    np.testing.assert_allclose(x.mean(axis=0), mean, atol=0.05)
    np.testing.assert_allclose(np.cov(x.T), cov, atol=0.05)
    y = np.asarray(rrandom.multi_variable_gaussian(RngState(9), mean, cov, 50000,
                                                   method="eig"))
    np.testing.assert_allclose(np.cov(y.T), cov, atol=0.05)


def test_rmat():
    theta = np.array([0.57, 0.19, 0.19, 0.05])
    out, src, dst = rrandom.rmat_rectangular_gen(RngState(10), theta, 10, 8, 5000)
    src, dst = np.asarray(src), np.asarray(dst)
    assert out.shape == (5000, 2)
    assert src.min() >= 0 and src.max() < 2**10
    assert dst.min() >= 0 and dst.max() < 2**8
    # skewed distribution: low ids dominate (a=0.57 upper-left)
    assert (src < 2**9).mean() > 0.65
