"""RNG + generator tests — counterpart of reference cpp/test/random/*
(moment-matching oracles, as in test/random/rng.cu)."""

import numpy as np
import pytest


from raft_tpu import random as rrandom
from raft_tpu.random import RngState


def test_rng_state_reproducible():
    a = rrandom.uniform(RngState(123), (1000,))
    b = rrandom.uniform(RngState(123), (1000,))
    c = rrandom.uniform(RngState(124), (1000,))
    np.testing.assert_array_equal(a, b)
    assert not np.allclose(a, c)


def test_rng_state_advances():
    st = RngState(5)
    a = rrandom.uniform(st, (100,))
    b = rrandom.uniform(st, (100,))
    assert not np.allclose(a, b)
    assert st.base_subsequence == 2


@pytest.mark.parametrize(
    "fn,kwargs,mean,std,tol",
    [
        (rrandom.uniform, dict(low=-1.0, high=3.0), 1.0, 4 / np.sqrt(12), 0.1),
        (rrandom.normal, dict(mu=2.0, sigma=0.5), 2.0, 0.5, 0.05),
        (rrandom.lognormal, dict(mu=0.0, sigma=0.25), np.exp(0.03125), None, 0.05),
        (rrandom.gumbel, dict(mu=0.0, beta=1.0), 0.5772, None, 0.05),
        (rrandom.logistic, dict(mu=1.0, scale=0.5), 1.0, None, 0.05),
        (rrandom.exponential, dict(lambda_=2.0), 0.5, None, 0.05),
        (rrandom.laplace, dict(mu=0.0, scale=1.0), 0.0, None, 0.1),
        (rrandom.rayleigh, dict(sigma=1.0), np.sqrt(np.pi / 2), None, 0.05),
    ],
)
def test_distribution_moments(fn, kwargs, mean, std, tol):
    x = np.asarray(fn(RngState(0), (40000,), **kwargs))
    assert abs(x.mean() - mean) < tol, f"{fn.__name__} mean {x.mean()} != {mean}"
    if std is not None:
        assert abs(x.std() - std) < tol


def test_uniform_int():
    x = np.asarray(rrandom.uniform_int(RngState(1), (10000,), 3, 9))
    assert x.min() == 3 and x.max() == 8


def test_bernoulli():
    x = np.asarray(rrandom.bernoulli(RngState(2), (20000,), prob=0.3))
    assert abs(x.mean() - 0.3) < 0.02
    y = np.asarray(rrandom.scaled_bernoulli(RngState(2), (1000,), prob=0.5, scale=2.0))
    assert set(np.unique(y)) == {-2.0, 2.0}


def test_normal_table():
    mu = np.array([0.0, 10.0, -5.0], np.float32)
    sig = np.array([1.0, 0.1, 2.0], np.float32)
    x = np.asarray(rrandom.normal_table(RngState(3), 20000, mu, sig))
    np.testing.assert_allclose(x.mean(axis=0), mu, atol=0.1)
    np.testing.assert_allclose(x.std(axis=0), sig, atol=0.1)


def test_discrete():
    w = np.array([0.1, 0.0, 0.6, 0.3])
    x = np.asarray(rrandom.discrete(RngState(4), (30000,), w))
    counts = np.bincount(x, minlength=4) / 30000
    np.testing.assert_allclose(counts, w, atol=0.02)
    assert counts[1] == 0


def test_sample_without_replacement():
    items = np.arange(100)
    out, idx = rrandom.sample_without_replacement(
        RngState(5), items, 30, return_indices=True
    )
    assert len(set(np.asarray(idx).tolist())) == 30  # no duplicates
    np.testing.assert_array_equal(np.asarray(out), items[np.asarray(idx)])
    # Heavily weighted item should essentially always be included
    w = np.ones(100)
    w[17] = 1e6
    hits = 0
    for seed in range(20):
        out = rrandom.sample_without_replacement(RngState(seed), items, 5, weights=w)
        hits += 17 in np.asarray(out)
    assert hits == 20


def test_permute():
    x = np.arange(50, dtype=np.float32).reshape(50, 1)
    out, perm = rrandom.permute(RngState(6), x)
    assert sorted(np.asarray(perm).tolist()) == list(range(50))
    np.testing.assert_allclose(np.asarray(out)[:, 0], np.asarray(perm))


def test_make_blobs():
    x, labels, centers = rrandom.make_blobs(
        RngState(7), 600, 8, n_clusters=4, cluster_std=0.1
    )
    assert x.shape == (600, 8) and labels.shape == (600,)
    # every point lies near its assigned center
    d = np.linalg.norm(np.asarray(x) - np.asarray(centers)[np.asarray(labels)], axis=1)
    assert d.max() < 2.0
    # roughly balanced clusters
    counts = np.bincount(np.asarray(labels), minlength=4)
    assert counts.min() >= 140


def test_make_regression():
    x, y, w = rrandom.make_regression(
        RngState(8), 200, 10, n_informative=5, noise=0.0, coef=True, shuffle=False
    )
    np.testing.assert_allclose(np.asarray(x) @ np.asarray(w), np.asarray(y), rtol=1e-3)
    assert np.allclose(np.asarray(w)[5:], 0)


def test_multi_variable_gaussian():
    mean = np.array([1.0, -2.0])
    cov = np.array([[2.0, 0.6], [0.6, 1.0]])
    x = np.asarray(rrandom.multi_variable_gaussian(RngState(9), mean, cov, 50000))
    np.testing.assert_allclose(x.mean(axis=0), mean, atol=0.05)
    np.testing.assert_allclose(np.cov(x.T), cov, atol=0.05)
    y = np.asarray(rrandom.multi_variable_gaussian(RngState(9), mean, cov, 50000,
                                                   method="eig"))
    np.testing.assert_allclose(np.cov(y.T), cov, atol=0.05)


def test_rmat():
    theta = np.array([0.57, 0.19, 0.19, 0.05])
    out, src, dst = rrandom.rmat_rectangular_gen(RngState(10), theta, 10, 8, 5000)
    src, dst = np.asarray(src), np.asarray(dst)
    assert out.shape == (5000, 2)
    assert src.min() >= 0 and src.max() < 2**10
    assert dst.min() >= 0 and dst.max() < 2**8
    # skewed distribution: low ids dominate (a=0.57 upper-left)
    assert (src < 2**9).mean() > 0.65


# ---------------------------------------------------------------------------
# Distributional oracles beyond first moments — the reference checks each
# generator against expected statistics per type/dtype (test/random/rng.cu
# MeanError grids, rng_int.cu); here each continuous distribution is held
# to a Kolmogorov–Smirnov test against its exact scipy CDF, which catches
# shape errors (wrong tails, truncation, transform bugs) that mean/std
# tolerances cannot.
# ---------------------------------------------------------------------------

scipy_stats = pytest.importorskip("scipy.stats")


@pytest.mark.parametrize(
    "fn,kwargs,dist,dist_args",
    [
        (rrandom.uniform, dict(low=-1.0, high=3.0), "uniform", (-1.0, 4.0)),
        (rrandom.normal, dict(mu=2.0, sigma=0.5), "norm", (2.0, 0.5)),
        (rrandom.lognormal, dict(mu=0.2, sigma=0.4), "lognorm",
         (0.4, 0, np.exp(0.2))),
        (rrandom.gumbel, dict(mu=1.0, beta=2.0), "gumbel_r", (1.0, 2.0)),
        (rrandom.logistic, dict(mu=-1.0, scale=0.7), "logistic", (-1.0, 0.7)),
        (rrandom.exponential, dict(lambda_=2.5), "expon", (0, 1 / 2.5)),
        (rrandom.rayleigh, dict(sigma=1.5), "rayleigh", (0, 1.5)),
        (rrandom.laplace, dict(mu=0.5, scale=1.2), "laplace", (0.5, 1.2)),
    ],
)
def test_distribution_ks(fn, kwargs, dist, dist_args):
    x = np.asarray(fn(RngState(21), (20000,), **kwargs), np.float64)
    stat, pvalue = scipy_stats.kstest(x, dist, args=dist_args)
    assert pvalue > 1e-3, (
        f"{fn.__name__} KS stat {stat:.4f} p={pvalue:.2e} vs {dist}{dist_args}")


def test_uniform_int_chi_square():
    """Every value in [low, high) equally likely (rng_int.cu role)."""
    low, high, n = 5, 21, 64000
    x = np.asarray(rrandom.uniform_int(RngState(22), (n,), low, high))
    counts = np.bincount(x - low, minlength=high - low)
    expected = n / (high - low)
    chi2 = ((counts - expected) ** 2 / expected).sum()
    # 15 dof: P(chi2 > 37.7) ≈ 1e-3
    assert chi2 < 37.7, f"chi2 {chi2:.1f}, counts {counts}"


def test_normal_int_moments():
    x = np.asarray(rrandom.normal_int(RngState(23), (40000,), 100, 7))
    assert x.dtype == np.int32
    assert abs(x.mean() - 100) < 0.5
    assert abs(x.std() - 7) < 0.5


def test_fill_exact():
    x = np.asarray(rrandom.fill(RngState(24), (7, 3), 2.5))
    np.testing.assert_array_equal(x, np.full((7, 3), 2.5, np.float32))


def test_subsequence_streams_uncorrelated():
    """Streams drawn from the SAME seed at successive subsequences must be
    independent (the reference's PhiloxGenerator subsequence contract)."""
    st = RngState(77)
    a = np.asarray(rrandom.normal(st, (20000,)))
    b = np.asarray(rrandom.normal(st, (20000,)))
    r = np.corrcoef(a, b)[0, 1]
    assert abs(r) < 0.02, f"successive streams correlate: r={r}"


def test_discrete_unnormalized_weights():
    """Weights need not sum to 1 (reference discrete_rng normalizes)."""
    w = np.array([2.0, 0.0, 6.0])
    x = np.asarray(rrandom.discrete(RngState(25), (30000,), w))
    counts = np.bincount(x, minlength=3) / 30000
    np.testing.assert_allclose(counts, w / w.sum(), atol=0.02)


def test_sample_without_replacement_full_draw():
    """n_samples == n is exactly a permutation: every item once."""
    items = np.arange(64)
    out = np.asarray(rrandom.sample_without_replacement(RngState(26), items, 64))
    assert sorted(out.tolist()) == list(range(64))


def test_sample_without_replacement_zero_weight_excluded():
    """Zero-weight items can never be drawn while positive-weight items
    remain (weighted reservoir property)."""
    items = np.arange(10)
    w = np.ones(10)
    w[[2, 5]] = 0.0
    for seed in range(10):
        out = np.asarray(rrandom.sample_without_replacement(
            RngState(seed), items, 8, weights=w))
        assert 2 not in out and 5 not in out


def test_permute_n_only_form():
    """permute(rng, n=...) returns a bare permutation of arange(n)."""
    perm = np.asarray(rrandom.permute(RngState(27), n=33))
    assert sorted(perm.tolist()) == list(range(33))


def test_permute_round_trip():
    """Applying the returned perm to the input reproduces the output, and
    the inverse perm restores the original (permute.cuh contract)."""
    x = np.random.default_rng(0).random((40, 5)).astype(np.float32)
    out, perm = rrandom.permute(RngState(28), x)
    out, perm = np.asarray(out), np.asarray(perm)
    np.testing.assert_allclose(out, x[perm])
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm))
    np.testing.assert_allclose(out[inv], x)


def test_make_blobs_no_shuffle_balanced():
    """shuffle=False: labels cycle 0..k-1 (the reference's balanced
    proportions default), and given centers are passed through."""
    centers = np.array([[0.0, 0.0], [100.0, 100.0]], np.float32)
    x, labels, c_out = rrandom.make_blobs(
        RngState(29), 10, 2, centers=centers, cluster_std=0.01, shuffle=False)
    np.testing.assert_array_equal(np.asarray(labels), np.arange(10) % 2)
    np.testing.assert_allclose(np.asarray(c_out), centers)
    np.testing.assert_allclose(np.asarray(x)[1], centers[1], atol=1.0)


def test_make_regression_noise_and_shuffle():
    """noise>0 perturbs y around x@w; shuffle preserves the (x, y) pairing."""
    x, y, w = rrandom.make_regression(
        RngState(30), 300, 8, n_informative=8, noise=0.1, coef=True,
        shuffle=True)
    resid = np.asarray(y) - np.asarray(x) @ np.asarray(w)
    assert 0.05 < resid.std() < 0.2  # noise scale honored after shuffling


def test_rmat_square_and_theta_normalization():
    """Square generator form; unnormalized theta is accepted (the
    reference normalizes per quadrant internally)."""
    theta = np.array([5.7, 1.9, 1.9, 0.5])  # 10x the usual, unnormalized
    out, src, dst = rrandom.rmat_rectangular_gen(RngState(31), theta, 6, 6,
                                                 4000)
    src, dst = np.asarray(src), np.asarray(dst)
    assert src.max() < 64 and dst.max() < 64
    # same skew as the normalized theta
    assert (src < 32).mean() > 0.65 and (dst < 32).mean() > 0.65


def test_degenerate_distribution_params():
    """sigma=0 normal collapses to the mean; uniform with lo==hi is
    constant — degenerate parameters must not NaN or crash."""
    import raft_tpu.random.rng as rngmod
    from raft_tpu.random import RngState

    out = np.asarray(rngmod.normal(RngState(0), (16,), 2.5, 0.0))
    np.testing.assert_allclose(out, 2.5, atol=1e-6)
    out = np.asarray(rngmod.uniform(RngState(0), (16,), 3.0, 3.0))
    np.testing.assert_allclose(out, 3.0, atol=1e-6)
