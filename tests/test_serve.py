"""Serving engine: coalesced ≡ per-request property grid, zero-compile
steady state, graceful out-of-range fallback, and the ci/lint.py serve
hot-path rule (raft_tpu/serve; docs/serving.md)."""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.core.aot import aot_compile_counters
from raft_tpu.neighbors import ivf_flat, ivf_pq, knn
from raft_tpu.serve import ServeEngine

_N, _DIM, _K = 2000, 16, 5

# ragged request mixes: empty, singletons, odd sizes, bucket-boundary and
# multi-super-batch totals — the shapes a coalescer must not mangle
_MIXES = [
    (3, 70, 1, 40, 0, 7),
    (16, 16, 1, 1, 1, 100),
    (1,),
    (127, 2),
]


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, (_N, _DIM)).astype(np.float32)
    return x, rng


_STATE = {}


def _index(backend: str):
    """Build each index once per module (builds dominate test time)."""
    if backend not in _STATE:
        x, _ = _data()
        if backend == "brute_force":
            _STATE[backend] = x
        elif backend == "ivf_flat":
            _STATE[backend] = ivf_flat.build(
                ivf_flat.IndexParams(n_lists=16), x)
        else:
            _STATE[backend] = ivf_pq.build(
                ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=8, seed=1),
                x)
    return _STATE[backend]


def _engine(backend: str, max_batch=128):
    idx = _index(backend)
    if backend == "brute_force":
        return ServeEngine(idx, _K, max_batch=max_batch)
    if backend == "ivf_flat":
        return ServeEngine(idx, _K, ivf_flat.SearchParams(n_probes=6),
                           max_batch=max_batch)
    return ServeEngine(idx, _K, ivf_pq.SearchParams(n_probes=6),
                       max_batch=max_batch)


def _solo(backend: str, q):
    idx = _index(backend)
    if backend == "brute_force":
        return knn(idx, q, _K)
    if backend == "ivf_flat":
        return ivf_flat.search(ivf_flat.SearchParams(n_probes=6), idx, q, _K)
    return ivf_pq.search(ivf_pq.SearchParams(n_probes=6), idx, q, _K)


@pytest.mark.parametrize("backend", [
    "brute_force", "ivf_flat",
    # tier-1 budget (ISSUE-20 rebalance): flat/brute carry the coalescing
    # identity; the pq serve path keeps warm-dispatch/refresh coverage in
    # the serve, autotune, and mutable batteries
    pytest.param("ivf_pq", marks=pytest.mark.slow),
])
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_coalesced_matches_per_request(backend, dtype):
    """The coalescing property: every request's (distances, indices) from a
    coalesced super-batch dispatch is IDENTICAL to solo dispatch of that
    request through the backend's public entry point — per-query rows of
    the search programs are independent of the rest of the batch, and the
    engine's ingest applies the same compute-form conversions the solo
    prologue does.  (ivf_pq ingests bf16 queries to f32 on both paths, as
    its reference is templated on T ∈ {float, int8, uint8}.)"""
    _, rng = _data()
    eng = _engine(backend)
    for mix in _MIXES:
        reqs = [rng.normal(0, 1, (s, _DIM)).astype(np.float32) for s in mix]
        if dtype == "bfloat16":
            reqs = [jnp.asarray(q, jnp.bfloat16) for q in reqs]
        outs = eng.search(reqs)
        assert len(outs) == len(reqs)
        for q, (d, i) in zip(reqs, outs):
            d0, i0 = _solo(backend, q)
            np.testing.assert_array_equal(i, np.asarray(i0))
            np.testing.assert_array_equal(d, np.asarray(d0))
    assert eng.stats["requests"] == sum(len(m) for m in _MIXES)


def test_zero_compiles_after_warmup():
    """The pinning contract (ISSUE 4 acceptance): after ``warmup()``,
    serving ANY request mix whose super-batches fall inside the warmed
    bucket range triggers zero new compiles/retraces — counter-asserted
    via core.aot.aot_compile_counters (every AotFunction cache miss bumps
    it)."""
    _, rng = _data(1)
    # max_batch 128 keeps every _MIXES request size (max 127) INSIDE the
    # warmed bucket range — out-of-range requests take the public solo
    # path, which is allowed to compile (covered by the fallback test)
    eng = _engine("brute_force", max_batch=128)
    n_sigs = eng.warmup()                       # buckets 8..128, f32
    assert n_sigs == 5
    assert eng.warmed_buckets(np.float32) == [8, 16, 32, 64, 128]
    # warm the engine's dispatch plumbing too (transfer paths, slicing)
    eng.search([rng.normal(0, 1, (3, _DIM)).astype(np.float32)])
    c0 = aot_compile_counters["compiles"]
    for mix in _MIXES:
        reqs = [rng.normal(0, 1, (s, _DIM)).astype(np.float32) for s in mix]
        eng.search(reqs)
    assert aot_compile_counters["compiles"] == c0, dict(aot_compile_counters)

    # counter liveness guard: an unwarmed signature MUST move the counter
    # (a dead counter would green-light a broken warmup forever).  A fresh
    # odd-shaped index guarantees the signature exists in no shared cache.
    eng2 = ServeEngine(rng.normal(0, 1, (53, _DIM)).astype(np.float32), 2,
                       max_batch=16)
    eng2.search([rng.normal(0, 1, (4, _DIM)).astype(np.float32)])
    assert aot_compile_counters["compiles"] > c0


def test_out_of_bucket_range_request_served_solo():
    """A request larger than the warmed bucket range (or max_batch) is
    served SOLO through the public entry point: counted in stats, results
    correct, coalesced path untouched — never a crash, never a silent
    recompile of a coalesced signature."""
    _, rng = _data(2)
    eng = _engine("brute_force", max_batch=64)
    eng.warmup(buckets=(8, 16))                 # narrow pinned range
    big = rng.normal(0, 1, (40, _DIM)).astype(np.float32)   # > 16, <= 64
    huge = rng.normal(0, 1, (200, _DIM)).astype(np.float32)  # > max_batch
    small = rng.normal(0, 1, (5, _DIM)).astype(np.float32)
    outs = eng.search([big, small, huge])
    assert eng.stats["solo_fallbacks"] == 2
    assert eng.stats["coalesced_requests"] == 1
    for q, (d, i) in zip([big, small, huge], outs):
        d0, i0 = _solo("brute_force", q)
        np.testing.assert_array_equal(i, np.asarray(i0))
        np.testing.assert_array_equal(d, np.asarray(d0))


def test_ingest_conversion_paths_match_solo():
    """The two non-trivial ingest prologues stay identical to solo
    dispatch: int8 queries (host-side exact widening to the compute
    dtype) and CosineExpanded (the one inexact prologue step — row
    normalize — which must reproduce the solo path's device numerics, so
    it alone round-trips the device; review finding, PR 4)."""
    rng = np.random.default_rng(6)
    x8 = rng.integers(-100, 100, (800, _DIM)).astype(np.int8)
    idx8 = ivf_flat.build(ivf_flat.IndexParams(n_lists=8), x8)
    eng8 = ServeEngine(idx8, 3, ivf_flat.SearchParams(n_probes=4),
                       max_batch=64)
    reqs8 = [x8[:5], x8[40:41]]
    for q, (d, i) in zip(reqs8, eng8.search(reqs8)):
        d0, i0 = ivf_flat.search(ivf_flat.SearchParams(n_probes=4),
                                 idx8, q, 3)
        np.testing.assert_array_equal(i, np.asarray(i0))
        np.testing.assert_array_equal(d, np.asarray(d0))

    xf, _ = _data(6)
    cidx = ivf_flat.build(ivf_flat.IndexParams(
        n_lists=8, metric=ivf_flat.DistanceType.CosineExpanded), xf)
    engc = ServeEngine(cidx, 3, ivf_flat.SearchParams(n_probes=4),
                       max_batch=64)
    reqs = [xf[:5], xf[100:123]]
    for q, (d, i) in zip(reqs, engc.search(reqs)):
        d0, i0 = ivf_flat.search(ivf_flat.SearchParams(n_probes=4),
                                 cidx, q, 3)
        np.testing.assert_array_equal(i, np.asarray(i0))
        np.testing.assert_array_equal(d, np.asarray(d0))


def test_mixed_dtype_stream_groups_by_dtype():
    """One call may carry f32 and bf16 requests: the coalescer groups per
    compute dtype (the one per-request signature dimension left once the
    engine pins (index, k, params)) and never packs across groups."""
    _, rng = _data(3)
    eng = _engine("brute_force")
    q32 = rng.normal(0, 1, (9, _DIM)).astype(np.float32)
    qbf = jnp.asarray(rng.normal(0, 1, (11, _DIM)), jnp.bfloat16)
    outs = eng.search([q32, qbf, q32[:2]])
    assert eng.stats["super_batches"] == 2      # one per dtype group
    np.testing.assert_array_equal(
        outs[0][1], np.asarray(knn(_index("brute_force"), q32, _K)[1]))
    np.testing.assert_array_equal(
        outs[1][1], np.asarray(knn(_index("brute_force"), qbf, _K)[1]))


def test_latency_telemetry_and_stats():
    _, rng = _data(4)
    eng = _engine("brute_force")
    reqs = [rng.normal(0, 1, (s, _DIM)).astype(np.float32)
            for s in (4, 0, 31)]
    eng.search(reqs)
    assert len(eng.last_latencies) == 3
    assert all(t >= 0.0 for t in eng.last_latencies)
    assert eng.stats["queries"] == 35
    assert eng.stats["requests"] == 3


class TestServeLintRule:
    """ci/lint.py's serve hot-path guard: jax.jit / jax.lax (and their
    from-imports) are forbidden inside raft_tpu/serve/ — the zero-retrace
    guarantee requires every device computation to route through the
    backends' aot() caches."""

    _VIOLATION = '''
import jax
import functools
from jax import lax
jitted = functools.partial(jax.jit, static_argnums=(0,))
def hot(x):
    return jax.lax.scan(lambda c, _: (c, None), x, None, length=3)
def hot2(x):
    return lax.fori_loop(0, 3, lambda i, c: c, x)
'''

    def _check(self, src):
        import ast

        from ci.lint import check_serve_hot_path

        return check_serve_hot_path(ast.parse(src), src.splitlines())

    def test_flags_jit_lax_and_from_imports(self):
        msgs = [m for _, m in self._check(self._VIOLATION)]
        assert any("jax.jit" in m for m in msgs)
        assert any("jax.lax.scan" in m for m in msgs)
        assert any("lax.fori_loop" in m for m in msgs)
        assert any("from jax import lax" in m for m in msgs)

    def test_import_laundering_does_not_evade(self):
        """`from jax.lax import X` and `import jax.lax as L` must not
        launder the dispatch past the rule (review finding, PR 4)."""
        src = ("from jax.lax import fori_loop\n"
               "import jax.lax as L\n"
               "def hot(x):\n"
               "    return L.scan(lambda c, _: (c, None), x, None, length=2)\n")
        msgs = [m for _, m in self._check(src)]
        assert any("from jax.lax import" in m for m in msgs)
        assert any("import jax.lax" in m for m in msgs)
        assert any("L.scan" in m for m in msgs)

    def test_marker_allowlists(self):
        src = "\n".join(ln + "  # serve-exempt: sanctioned"
                        if ("jax." in ln or "import lax" in ln
                            or "lax.fori" in ln) else ln
                        for ln in self._VIOLATION.splitlines())
        assert self._check(src) == []

    def test_scoped_to_serve(self, tmp_path):
        from ci.lint import check_file

        d = tmp_path / "raft_tpu" / "serve"
        d.mkdir(parents=True)
        f = d / "mod.py"
        f.write_text(self._VIOLATION)
        assert any("aot() executable cache" in m for _, m in check_file(f))
        other = tmp_path / "raft_tpu" / "cluster"
        other.mkdir()
        g = other / "mod.py"
        g.write_text(self._VIOLATION)
        assert not any("aot() executable cache" in m
                       for _, m in check_file(g))

    def test_shipped_serve_tree_clean(self):
        import pathlib

        from ci.lint import check_file

        root = pathlib.Path(__file__).resolve().parents[1]
        for f in sorted((root / "raft_tpu" / "serve").glob("*.py")):
            assert not check_file(f), f
