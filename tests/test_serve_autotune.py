"""Online autotuner battery (ISSUE 19, docs/serving.md §autotuning):
candidate space bounded by the warmed ladder, seeded determinism of the
candidate schedule + promote/reject decisions, zero-compile explore →
promote counter asserts, params promotion through the atomic refresh
swap, guarded rollback, per-lane cost-EWMA gradual shedding under an
injected stalled lane, and AOT-store cost-row cold-start seeding."""

import threading

import numpy as np
import pytest

from raft_tpu import telemetry
from raft_tpu.comms import build_comms
from raft_tpu.core import aotstore
from raft_tpu.core.aot import aot_compile_counters
from raft_tpu.core.error import RaftError
from raft_tpu.neighbors import ann_mnmg, ivf_flat, knn
from raft_tpu.serve import AutoTuner, Candidate, ServeEngine, TunerConfig
from raft_tpu.serve.autotune import BASELINE, Score, exact_reference
from raft_tpu.serve.schedule import CostModel, ReplicaRouter
from raft_tpu.testing import faults

_DIM = 16
_K = 4


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(7)
    return rng.normal(0, 1, (1024, _DIM)).astype(np.float32)


@pytest.fixture(scope="module")
def fl_index(corpus):
    return ivf_flat.build(
        ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), corpus)


def _reqs(seed=1, sizes=(3, 7, 2, 6, 1, 5)):
    rng = np.random.default_rng(seed)
    return [rng.normal(0, 1, (n, _DIM)).astype(np.float32)
            for n in sizes]


def _bf_engine(corpus, max_batch=32):
    eng = ServeEngine(corpus, _K, max_batch=max_batch)
    eng.warmup()
    return eng


class TestCandidateSpace:
    def test_candidates_derive_from_warmed_ladder(self, corpus):
        eng = _bf_engine(corpus)
        try:
            tuner = AutoTuner(eng, TunerConfig(seed=3))
            names = [c.name for c in tuner.candidates()]
            # baseline + one cap per warmed bucket below the serving cap
            assert names == ["baseline", "cap8", "cap16"]
            # every cap candidate IS a warmed bucket (zero-compile by
            # construction: the space is a subset of the ladder)
            warmed = {b for bs in eng.warmed_signatures().values()
                      for b in bs}
            for c in tuner.candidates():
                if c.max_batch is not None:
                    assert c.max_batch in warmed
        finally:
            eng.close()

    def test_candidates_before_warmup_raise(self, corpus):
        eng = ServeEngine(corpus, _K, max_batch=32)
        try:
            with pytest.raises(RaftError):
                AutoTuner(eng).candidates()
        finally:
            eng.close()

    def test_overbound_subsample_is_seeded(self, corpus):
        eng = _bf_engine(corpus, max_batch=64)
        try:
            extra = tuple(Candidate(f"q{i}", quantum_s=0.001 * (i + 1))
                          for i in range(8))
            cfg = TunerConfig(seed=11, max_candidates=4)
            a = [c.name for c in
                 AutoTuner(eng, cfg, extra_candidates=extra).candidates()]
            b = [c.name for c in
                 AutoTuner(eng, cfg, extra_candidates=extra).candidates()]
            assert a == b and len(a) == 4 and a[0] == "baseline"
        finally:
            eng.close()


def _fake_measure(log, winner="cap16"):
    """A deterministic injected measurement stream: *winner* beats the
    baseline on qps at equal p99 in every pair; everything else loses.
    Logs (candidate, stream fingerprint) so replays can be compared."""
    def measure(cand, requests):
        fp = tuple(round(float(q[0, 0]), 5) for q in requests)
        log.append((cand.name, len(requests), fp))
        if cand.name == winner:
            return Score(qps=150.0, p99_s=0.010, recall=1.0)
        if cand.name == BASELINE.name:
            return Score(qps=100.0, p99_s=0.010, recall=1.0)
        return Score(qps=90.0, p99_s=0.012, recall=1.0)
    return measure


class TestDeterminism:
    def _run_once(self, corpus, seed=5):
        eng = _bf_engine(corpus)
        try:
            eng.search(_reqs(seed=2))  # populate the shadow ring
            log = []
            tuner = AutoTuner(eng, TunerConfig(seed=seed, pairs=2,
                                               shadow_requests=6),
                              measure=_fake_measure(log))
            report = tuner.run()
            return report, log, eng.max_batch
        finally:
            eng.close()

    def test_same_seed_same_schedule_and_decisions(self, corpus):
        """Same seed + same measurement stream ⇒ bit-identical candidate
        schedule, shadow-traffic stream, and promote/reject decisions
        (the testing/faults.py determinism contract)."""
        r1, log1, mb1 = self._run_once(corpus)
        r2, log2, mb2 = self._run_once(corpus)
        assert r1 == r2
        assert log1 == log2  # identical shadow sampling per seed
        assert mb1 == mb2 == 16  # cap16 promoted both times
        assert r1["winner"] == "cap16"
        assert ("cap16", "promote", "paired win") in [
            tuple(d) for d in r1["decisions"]]

    def test_different_seed_different_stream(self, corpus):
        _, log1, _ = self._run_once(corpus, seed=5)
        _, log2, _ = self._run_once(corpus, seed=6)
        assert [t[:2] for t in log1] == [t[:2] for t in log2]
        assert log1 != log2  # sampling follows the seed

    def test_coverage_rule_rejects_skip_heavy_candidates(self, corpus):
        """A candidate that scores a higher qps by SERVING FEWER of the
        pair's requests (skipping above its cap) must be coverage-
        rejected, not promoted — qps over a shrunken set is not a win."""
        eng = _bf_engine(corpus)
        try:
            eng.search(_reqs(seed=2))

            def measure(cand, requests):
                if cand.name == "cap8":  # fast BECAUSE it skips half
                    return Score(qps=500.0, p99_s=0.001, recall=1.0,
                                 served=0.5)
                return Score(qps=100.0, p99_s=0.010, recall=1.0)

            tuner = AutoTuner(eng, TunerConfig(seed=0, pairs=2,
                                               shadow_requests=6),
                              measure=measure)
            report = tuner.run()
            assert report["winner"] != "cap8"
            assert ("cap8", "reject", "coverage") in [
                tuple(d) for d in report["decisions"]]
            assert eng.max_batch == 32
        finally:
            eng.close()

    def test_losing_candidates_are_rejected_not_promoted(self, corpus):
        eng = _bf_engine(corpus)
        try:
            eng.search(_reqs(seed=2))
            log = []
            tuner = AutoTuner(eng, TunerConfig(seed=1, pairs=2,
                                               shadow_requests=6),
                              measure=_fake_measure(log, winner="nobody"))
            report = tuner.run()
            assert report["winner"] is None
            assert all(d[1] == "reject" for d in report["decisions"])
            assert eng.max_batch == 32  # nothing applied
        finally:
            eng.close()


class TestZeroCompile:
    def test_explore_and_promote_are_zero_compile(self, corpus):
        """The acceptance gate's counter assert: a full real-measure
        explore over the warmed-cap candidates, then a forced promotion
        and post-promotion serving, with ZERO aot compiles end to end."""
        eng = _bf_engine(corpus)
        try:
            eng.search(_reqs(seed=3))
            tuner = AutoTuner(eng, TunerConfig(seed=0, pairs=1,
                                               shadow_requests=8))
            c0 = aot_compile_counters["compiles"]
            tuner.warm_candidates()  # no params variants: nothing to lower
            tuner.explore()
            tuner.promote(Candidate("cap16", max_batch=16))
            outs = eng.search(_reqs(seed=4))
            assert aot_compile_counters["compiles"] == c0, \
                dict(aot_compile_counters)
            assert eng.max_batch == 16
            for q, (d, i) in zip(_reqs(seed=4), outs):
                _, i0 = knn(corpus, q, _K)
                np.testing.assert_array_equal(i, np.asarray(i0))
        finally:
            eng.close()

    def test_params_promotion_via_refresh_zero_compile(self, fl_index):
        """A backend-params candidate: warm_candidates pre-lowers its
        shadow backend (compiles sanctioned there), after which explore,
        the refresh-swap promotion, AND post-promotion serving are all
        pure cache hits — and the engine serves the NEW params."""
        sp0 = ivf_flat.SearchParams(n_probes=2)
        sp1 = ivf_flat.SearchParams(n_probes=6)
        eng = ServeEngine(fl_index, _K, sp0, max_batch=16)
        eng.warmup()
        try:
            eng.search(_reqs(seed=5))
            tuner = AutoTuner(eng, TunerConfig(seed=0, pairs=1,
                                               shadow_requests=6),
                              param_variants=[sp1])
            assert tuner.warm_candidates() > 0  # lowered the variant
            c0 = aot_compile_counters["compiles"]
            score = tuner._measure_real(Candidate("params0", params=sp1),
                                        _reqs(seed=6))
            assert score.qps > 0 and 0.0 <= score.recall <= 1.0
            tuner.promote(Candidate("params0", params=sp1))
            outs = eng.search(_reqs(seed=7))
            assert aot_compile_counters["compiles"] == c0, \
                dict(aot_compile_counters)
            for q, (d, i) in zip(_reqs(seed=7), outs):
                _, i1 = ivf_flat.search(sp1, fl_index, q, _K)
                np.testing.assert_array_equal(i, np.asarray(i1))
        finally:
            eng.close()

    def test_recall_probe_against_exact_reference(self, corpus):
        eng = _bf_engine(corpus)
        try:
            eng.search(_reqs(seed=8))
            tuner = AutoTuner(eng, TunerConfig(seed=0, pairs=1,
                                               shadow_requests=6),
                              reference=exact_reference(corpus, _K))
            # brute force IS exact: the probe must certify perfect recall
            score = tuner._measure_real(Candidate("cap16", max_batch=16),
                                        _reqs(seed=9))
            assert score.recall == 1.0
        finally:
            eng.close()


class TestShadowIsolation:
    def test_shadow_dispatch_serializes_under_engine_lock(self, corpus):
        """REVIEW medium: a knob candidate replays through the LIVE
        backend's warmed executables — each dispatch must take the
        engine lock (the ServeEngine thread-safety contract), so an
        off-thread explore can never interleave with a live search()'s
        planning/dispatch."""
        eng = _bf_engine(corpus)
        try:
            eng.search(_reqs(seed=2))
            tuner = AutoTuner(eng, TunerConfig(seed=0, pairs=1,
                                               shadow_requests=4))
            done = threading.Event()
            out = {}

            def shadow():
                out["score"] = tuner._measure_real(
                    Candidate("cap16", max_batch=16), _reqs(seed=3))
                done.set()

            with eng._lock:  # a live search() in flight
                t = threading.Thread(target=shadow)
                t.start()
                # the replay queues behind the lock instead of racing it
                assert not done.wait(0.2)
            t.join(10.0)
            assert done.is_set()
            assert out["score"].qps > 0 and out["score"].served == 1.0
        finally:
            eng.close()

    def test_live_search_racing_shadow_replay(self, corpus):
        """Smoke: live search() calls interleaved with shadow replays on
        another thread — every live result stays bit-identical to solo
        (the shared stream pool is never entered concurrently)."""
        eng = _bf_engine(corpus)
        try:
            eng.search(_reqs(seed=2))
            tuner = AutoTuner(eng, TunerConfig(seed=0, pairs=1,
                                               shadow_requests=4))
            stop = threading.Event()
            errs = []

            def shadow():
                while not stop.is_set():
                    try:
                        tuner._measure_real(
                            Candidate("cap16", max_batch=16),
                            _reqs(seed=5))
                    except Exception as e:  # pragma: no cover
                        errs.append(e)
                        return

            t = threading.Thread(target=shadow)
            t.start()
            try:
                for s in range(5):
                    reqs = _reqs(seed=20 + s)
                    outs = eng.search(reqs)
                    for q, (d, i) in zip(reqs, outs):
                        _, i0 = knn(corpus, q, _K)
                        np.testing.assert_array_equal(i, np.asarray(i0))
            finally:
                stop.set()
                t.join(10.0)
            assert not errs
        finally:
            eng.close()

    def test_shadow_sampling_without_replacement(self, corpus):
        """REVIEW low: a ring larger than the budget contributes distinct
        live requests (no needless duplicates); a ring smaller than the
        budget contributes EVERY live request exactly once."""
        eng = _bf_engine(corpus)
        try:
            eng.search(_reqs(seed=2))  # 6 ring entries
            tuner = AutoTuner(eng, TunerConfig(seed=0))
            reqs = tuner.shadow_traffic(4, seed=1)
            assert len(reqs) == 4
            assert len({id(q) for q in reqs}) == 4
            reqs = tuner.shadow_traffic(50, seed=1)  # budget > ring
            assert len(reqs) == 6
            assert len({id(q) for q in reqs}) == 6
        finally:
            eng.close()


class TestRollback:
    def test_live_p99_regression_rolls_back(self, corpus):
        eng = _bf_engine(corpus)
        try:
            eng.search(_reqs(seed=3))
            tuner = AutoTuner(eng, TunerConfig(seed=0))
            tuner.promote(Candidate("cap16", max_batch=16))
            assert eng.max_batch == 16
            pre = tuner._pre_p99
            assert pre is not None and pre > 0.0
            # inside the window, a p99 blowup reverts the whole decision
            assert tuner.maybe_rollback(live_p99_s=100.0 * pre) is True
            assert eng.max_batch == 32
            assert tuner.decisions[-1][1] == "rollback"
            # the guard disarmed: a second regression report is a no-op
            assert tuner.maybe_rollback(live_p99_s=100.0 * pre) is False
        finally:
            eng.close()

    def test_params_rollback_on_params_none_engine(self, fl_index):
        """THE guarded-rollback regression (REVIEW high): an engine
        constructed with params=None promotes a params candidate, live
        p99 regresses, and the rollback must restore the params=None
        construction — refresh applies the token's None VERBATIM
        (KEEP_PARAMS semantics) instead of treating it as 'keep the
        regressing candidate's params'."""
        sp1 = ivf_flat.SearchParams(n_probes=6)
        eng = ServeEngine(fl_index, _K, max_batch=16)  # params=None
        eng.warmup()
        try:
            eng.search(_reqs(seed=3))  # arm the guard with a baseline
            tuner = AutoTuner(eng, TunerConfig(seed=0),
                              param_variants=[sp1])
            tuner.warm_candidates()
            tuner.promote(Candidate("params0", params=sp1))
            assert eng._ctor["params"] is sp1
            assert eng._backend.n_probes == 6
            pre = tuner._pre_p99
            assert pre is not None and pre > 0.0
            assert tuner.maybe_rollback(live_p99_s=100.0 * pre) is True
            # the rollback actually took: ctor params are None again and
            # the engine serves the library-default config
            assert eng._ctor["params"] is None
            assert eng._backend.n_probes == min(
                ivf_flat.SearchParams().n_probes, fl_index.n_lists)
            outs = eng.search(_reqs(seed=4))
            for q, (d, i) in zip(_reqs(seed=4), outs):
                _, i0 = ivf_flat.search(ivf_flat.SearchParams(),
                                        fl_index, q, _K)
                np.testing.assert_array_equal(i, np.asarray(i0))
        finally:
            eng.close()

    def test_params_promotion_preserves_tuned_cap(self, fl_index):
        """REVIEW medium: refresh() re-derives max_batch from the
        construction bound — a cap promoted by an earlier tune cycle
        must survive a later params-only promotion, and the later
        promotion's rollback token must carry the TUNED cap, not the
        construction default."""
        sp1 = ivf_flat.SearchParams(n_probes=6)
        eng = ServeEngine(fl_index, _K, max_batch=16)
        eng.warmup()
        try:
            eng.search(_reqs(seed=3))
            tuner = AutoTuner(eng, TunerConfig(seed=0),
                              param_variants=[sp1])
            tuner.warm_candidates()
            tuner.promote(Candidate("cap8", max_batch=8))  # cycle 1
            assert eng.max_batch == 8
            prev = tuner.promote(Candidate("params0", params=sp1))
            assert eng.max_batch == 8  # cycle 2 left the cap alone
            assert prev["max_batch"] == 8  # token: pre-promotion state
        finally:
            eng.close()

    def test_promotion_without_baseline_disarms_guard(self, corpus):
        """REVIEW low: promoting with NO pre-promotion p99 baseline (no
        live traffic yet) cannot arm the guard — /healthz must report
        rollback_window_open=false (not advertise a guard it cannot
        enforce) and the disarm is counted."""
        eng = _bf_engine(corpus)
        try:
            tuner = AutoTuner(eng, TunerConfig(seed=0))
            tuner.promote(Candidate("cap16", max_batch=16))
            assert tuner._pre_p99 is None
            body = eng._health()
            assert body["autotune"]["promoted"] == "cap16"
            assert body["autotune"]["rollback_window_open"] is False
            disarmed = telemetry.REGISTRY.get(
                "raft_tpu_autotune_guard_disarmed_total")
            assert sum(v for labels, v in disarmed.items()
                       if labels == (eng._engine_id,)) == 1
            # an unguarded promotion is accepted as-is: a later p99
            # report cannot roll it back
            assert tuner.maybe_rollback(live_p99_s=1e9) is False
            assert eng.max_batch == 16
            assert tuner._promoted is None
        finally:
            eng.close()

    def test_healthy_p99_keeps_promotion(self, corpus):
        eng = _bf_engine(corpus)
        try:
            eng.search(_reqs(seed=3))
            tuner = AutoTuner(eng, TunerConfig(seed=0))
            tuner.promote(Candidate("cap16", max_batch=16))
            assert tuner.maybe_rollback(
                live_p99_s=tuner._pre_p99) is False
            assert eng.max_batch == 16
            # window expiry accepts the promotion and disarms the guard
            tuner._promoted_at -= (tuner.cfg.rollback_window_s + 1.0)
            assert tuner.maybe_rollback(live_p99_s=1e9) is False
            assert tuner._promoted is None
        finally:
            eng.close()

    def test_apply_tuning_rejects_unwarmed_cap(self, corpus):
        eng = _bf_engine(corpus)
        try:
            with pytest.raises(RaftError):
                eng.apply_tuning(max_batch=24)  # not a warmed bucket
            assert eng.max_batch == 32
        finally:
            eng.close()


class TestRefreshParamsSentinel:
    def test_refresh_keeps_vs_applies_none(self, fl_index):
        """refresh() params semantics: omitted (KEEP_PARAMS) keeps the
        current serving params; an EXPLICIT None applies the backend's
        library defaults — the distinction the tuner's rollback token
        relies on."""
        sp = ivf_flat.SearchParams(n_probes=6)
        eng = ServeEngine(fl_index, _K, sp, max_batch=16)
        eng.warmup()
        try:
            eng.refresh(fl_index)  # default: keep current params
            assert eng._ctor["params"] is sp
            assert eng._backend.n_probes == 6
            eng.refresh(fl_index, params=None)  # explicit: defaults
            assert eng._ctor["params"] is None
            assert eng._backend.n_probes == min(
                ivf_flat.SearchParams().n_probes, fl_index.n_lists)
        finally:
            eng.close()


class TestHealthAndVarz:
    def test_decisions_visible_in_healthz_and_registry(self, corpus):
        eng = _bf_engine(corpus)
        try:
            eng.search(_reqs(seed=2))
            log = []
            tuner = AutoTuner(eng, TunerConfig(seed=5, pairs=2,
                                               shadow_requests=6),
                              measure=_fake_measure(log))
            tuner.run()
            body = eng._health()
            assert body["autotune"]["promoted"] == "cap16"
            assert body["autotune"]["rollback_window_open"] is True
            assert body["autotune"]["evaluations"] == len(tuner.schedule)
            text = telemetry.prometheus_text()
            assert "raft_tpu_autotune_decisions_total" in text
            assert "raft_tpu_autotune_qps" in text
            dec = telemetry.REGISTRY.get("raft_tpu_autotune_decisions_total")
            promoted = sum(v for labels, v in dec.items()
                           if labels == (eng._engine_id, "promote"))
            assert promoted == 1
        finally:
            eng.close()


class TestLaneCostShedding:
    def test_router_ewma_sheds_gradually(self):
        r = ReplicaRouter(2, "t-ewma")
        # unobserved lanes are equal-cost: round-robin-ish booking
        assert r.slowness(0) == r.slowness(1) == 1.0
        for _ in range(4):
            r.observe(0, 0.001)
            r.observe(1, 0.010)
        assert r.slowness(0) == 1.0
        assert r.slowness(1) > 5.0
        # pick books the slow lane's completion at slowness x est: the
        # fast lane absorbs several batches before the slow one is next
        picks = [r.pick(0.0, 0.001) for _ in range(10)]
        assert picks.count(0) > picks.count(1)
        assert picks.count(1) >= 1  # gradual shedding, not a drain
        assert r.degraded_lanes() == []

    def test_drain_is_not_a_fault(self):
        r = ReplicaRouter(2, "t-drain")
        r.drain(1)
        assert r.degraded_lanes() == [1]
        assert r.pick(0.0, 0.001) == 0
        faults_c = telemetry.REGISTRY.get(
            "raft_tpu_serve_replica_faults_total")
        assert all(labels[0] != "t-drain"
                   for labels, v in faults_c.items() if v > 0)
        r.restore(1)
        assert r.degraded_lanes() == []

    def test_stalled_lane_sheds_load_but_stays_live(self, fl_index):
        """The PR 14 fault plane injects a persistent stall on lane 1:
        its cost EWMA inflates, the router books it at its observed
        slowness, and load gradually shifts to lane 0 — WITHOUT draining
        lane 1 (a slow lane is capacity, not a fault) and with every
        request correctly served."""
        replica_set = ann_mnmg.replicate(fl_index, build_comms(), 2)
        sp = ivf_flat.SearchParams(n_probes=3)
        eng = ServeEngine(replica_set, _K, sp, max_batch=8)
        eng.warmup()
        try:
            eng.search(_reqs(seed=1, sizes=(2,)))  # plumbing warm call
            disp = telemetry.REGISTRY.get(
                "raft_tpu_serve_replica_dispatch_total")

            def lane_counts():
                return {labels[1]: v for labels, v in disp.items()
                        if labels[0] == eng._engine_id}

            base = lane_counts()
            with faults.plan(
                    "comms:op=replica_dispatch:rank=1:stall=0.03:times=0"):
                for s in range(6):
                    reqs = _reqs(seed=10 + s, sizes=(5, 6, 7, 5, 6, 7))
                    outs = eng.search(reqs)
                    for q, (d, i) in zip(reqs, outs):
                        _, i0 = ivf_flat.search(sp, fl_index, q, _K)
                        np.testing.assert_array_equal(i, np.asarray(i0))
            counts = lane_counts()
            to0 = counts.get("0", 0) - base.get("0", 0)
            to1 = counts.get("1", 0) - base.get("1", 0)
            assert to0 > to1  # the stalled lane shed load...
            assert to1 >= 1   # ...gradually — it still serves
            assert eng._health()["replicas"]["degraded"] == []
            cost = telemetry.REGISTRY.get(
                "raft_tpu_serve_replica_cost_seconds")
            lanes = {labels[1]: v for labels, v in cost.items()
                     if labels[0] == eng._engine_id}
            assert lanes["1"] > lanes["0"]  # the EWMA saw the stall
        finally:
            eng.close()


class TestCostColdStart:
    def test_seed_rows_fills_absent_only(self):
        cm = CostModel(use_telemetry=False, static_batch_s=0.5)
        cm.observe("float32", 8, 0.001)
        n = cm.seed_rows({("float32", 8): 0.9, ("float32", 16): 0.002,
                          ("bfloat16", 8): -1.0})
        assert n == 1  # live row kept, negative row dropped
        rows = cm.rows()
        assert rows[("float32", 8)] == pytest.approx(0.001)
        assert rows[("float32", 16)] == pytest.approx(0.002)

    def test_engine_seeds_cost_model_from_store(self, corpus, tmp_path):
        """The cold-start fix: close() persists the observed per-(dtype,
        bucket) cost rows into the installed AOT store; a NEW engine over
        the same serving key seeds its scheduler cost model from them at
        construction — real costs on the first decision, not the static
        fallback."""
        prev = aotstore.install(str(tmp_path))
        try:
            eng = _bf_engine(corpus)
            eng.search(_reqs(seed=2))
            fn = eng._backend_fn()
            observed = eng._cost.rows()
            assert observed  # serving produced real rows
            eng.close()
            store = aotstore.installed()
            persisted = store.load_costs(fn)
            assert persisted
            for key, v in observed.items():
                assert persisted[key] == pytest.approx(v)

            eng2 = ServeEngine(corpus, _K, max_batch=32)
            try:
                seeded = eng2._cost.rows()
                for key, v in persisted.items():
                    assert seeded[key] == pytest.approx(v)
            finally:
                eng2.close()
        finally:
            aotstore.install(prev)

    def test_no_store_is_a_clean_noop(self, corpus):
        prev = aotstore.install(None)
        try:
            eng = _bf_engine(corpus)
            assert eng._cost.rows() == {}
            eng.close()
        finally:
            aotstore.install(prev)
