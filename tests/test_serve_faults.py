"""The fault-injection battery (ISSUE 14; docs/serving.md §failure
model): deadline-aware admission + load shedding, supervised dispatch
(watchdog / bounded retry / per-request isolation), refresh atomicity
under injected crashes, bounded idempotent shutdown, and the trace-time
guarantee that the fault plane adds NOTHING to lowered programs."""

import contextlib
import json
import threading
import time

import numpy as np
import pytest

import jax

from raft_tpu.core.aot import aot_compile_counters
from raft_tpu.core.error import LogicError
from raft_tpu.neighbors import knn
from raft_tpu.serve import (AdmissionController, RejectedError, ServeEngine,
                            ServeRequest, WatchdogTimeout)
from raft_tpu.serve.supervise import retryable
from raft_tpu.testing import faults

_N, _DIM, _K = 2000, 16, 5


def _data(seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(0, 1, (_N, _DIM)).astype(np.float32), rng


_X, _RNG = _data()
_X2 = _data(7)[0]


def _engine(max_batch=64, **kw):
    eng = ServeEngine(_X, _K, max_batch=max_batch, **kw)
    eng.warmup()
    eng.search([_X[:2]])  # warm the dispatch plumbing too
    return eng


def _solo(x, q):
    d, i = knn(x, q, _K)
    return np.asarray(d), np.asarray(i)


# ---------------------------------------------------------------------------
# the plan grammar


class TestFaultPlan:
    def test_parse_fields(self):
        p = faults.FaultPlan.parse(
            "dispatch:n=3:raise; dispatch:n=5:stall=0.5;"
            "comms:rank=1:op=isend:fail; refresh:stage=pre_swap:crash;"
            "dispatch:p=0.25:seed=9:raise=logic")
        d = p.directives
        assert (d[0].site, d[0].n, d[0].action, d[0].kind) == (
            "dispatch", 3, "raise", "transient")
        assert (d[1].action, d[1].stall_s) == ("stall", 0.5)
        assert (d[2].site, d[2].rank, d[2].op) == ("comms", 1, "isend")
        assert (d[3].site, d[3].stage) == ("refresh", "pre_swap")
        assert (d[4].p, d[4].seed, d[4].kind) == (0.25, 9, "logic")

    @pytest.mark.parametrize("bad", [
        "", "bogus:n=1:raise", "dispatch:n=1",        # no action
        "dispatch:wat=1:raise", "dispatch:raise=wat",
    ])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            faults.FaultPlan.parse(bad)

    def test_nth_event_and_times(self):
        p = faults.FaultPlan.parse("dispatch:n=2:times=2:raise")
        p.check("dispatch")                       # event 1: silent
        for _ in range(2):                        # events 2, 3: fire
            with pytest.raises(faults.InjectedFault):
                p.check("dispatch")
        p.check("dispatch")                       # event 4: silent again

    def test_attribute_filters_gate_counting(self):
        p = faults.FaultPlan.parse("comms:rank=1:n=1:fail")
        p.check("comms", rank=0, op="isend")      # filtered out, not counted
        with pytest.raises(faults.InjectedFault):
            p.check("comms", rank=1, op="isend")  # 1st MATCHING event

    def test_seeded_probability_is_deterministic(self):
        def seq():
            p = faults.FaultPlan.parse("dispatch:p=0.4:seed=3:times=0:raise")
            out = []
            for _ in range(32):
                try:
                    p.check("dispatch")
                    out.append(0)
                except faults.InjectedFault:
                    out.append(1)
            return out

        a, b = seq(), seq()
        assert a == b and 0 < sum(a) < 32

    def test_off_by_default_and_context_restores(self):
        assert faults.active_plan() is None
        with faults.plan("dispatch:n=1:raise") as p:
            assert faults.active_plan() is p
        assert faults.active_plan() is None

    def test_retryable_classification(self):
        assert retryable(faults.InjectedFault("x"))
        assert retryable(WatchdogTimeout("x"))
        assert retryable(RuntimeError("transient"))
        assert not retryable(faults.InjectedLogicFault("x"))
        assert not retryable(LogicError("shape bug"))
        assert not retryable(TypeError("x"))
        assert not retryable(ValueError("x"))


# ---------------------------------------------------------------------------
# supervised dispatch: retry, watchdog, isolation


class TestSupervisedDispatch:
    def test_transient_fault_retry_bit_identical_zero_compile(self):
        """A transient dispatch failure is retried through the SAME warmed
        executable: results bit-identical to solo, zero compiles during
        the faulted replay (acceptance gate)."""
        eng = _engine()
        reqs = [_X[:3], _X[10:17], _X[40:41]]
        c0 = aot_compile_counters["compiles"]
        with faults.plan("dispatch:n=1:raise"):
            outs = eng.search(reqs)
        assert aot_compile_counters["compiles"] == c0, \
            "retry path compiled (bucket ladder not reused)"
        assert eng.stats["retries"] >= 1
        for q, (d, i) in zip(reqs, outs):
            d0, i0 = _solo(_X, q)
            np.testing.assert_array_equal(i, i0)
            np.testing.assert_array_equal(d, d0)

    def test_watchdog_fires_and_engine_recovers(self):
        """A hung dispatch trips the wall-clock watchdog instead of
        blocking the engine forever; the retry re-dispatches fresh buffers
        and the engine stays serviceable (acceptance gate)."""
        eng = _engine(watchdog_s=0.25, max_retries=1)
        t0 = time.monotonic()
        with faults.plan("dispatch:n=1:stall=5"):
            outs = eng.search([_X[:3]])
        wall = time.monotonic() - t0
        assert wall < 3.0, f"engine waited the stall out ({wall:.1f}s)"
        assert eng.stats["watchdog_timeouts"] == 1
        np.testing.assert_array_equal(outs[0][1], _solo(_X, _X[:3])[1])
        # and the engine is fully serviceable afterwards
        outs = eng.search([_X[5:9]])
        np.testing.assert_array_equal(outs[0][1], _solo(_X, _X[5:9])[1])

    def test_persistent_hang_fails_typed_then_recovers(self):
        eng = _engine(watchdog_s=0.2, max_retries=0)
        with faults.plan("dispatch:n=1:times=0:stall=5"):
            outs = eng.search([_X[:3]])
        assert isinstance(outs[0], WatchdogTimeout)
        outs = eng.search([_X[:3]])  # plan gone: engine serves again
        np.testing.assert_array_equal(outs[0][1], _solo(_X, _X[:3])[1])

    def test_nonretryable_fails_fast_and_isolates(self):
        """A non-retryable (logic) failure is NEVER retried; the failed
        multi-member super-batch is split and re-dispatched member-by-
        member through the warmed bucket ladder (zero-compile), so the
        healthy members are served."""
        eng = _engine()
        r0 = eng.stats["retries"]
        reqs = [_X[:3], _X[10:17]]
        c0 = aot_compile_counters["compiles"]
        with faults.plan("dispatch:n=1:raise=logic"):
            outs = eng.search(reqs)
        assert aot_compile_counters["compiles"] == c0, \
            "isolation split compiled (ladder not warmed?)"
        assert eng.stats["retries"] == r0, "a logic fault was retried"
        assert eng.stats["isolation_splits"] == 1
        for q, (d, i) in zip(reqs, outs):
            np.testing.assert_array_equal(i, _solo(_X, q)[1])

    def test_poisoned_request_fails_alone(self):
        """Per-request isolation at ingest: one malformed request gets its
        typed error in its slot; every other request is served."""
        eng = _engine()
        bad = np.zeros((3, _DIM + 2), np.float32)  # wrong dim
        outs = eng.search([_X[:3], bad, _X[5:9]])
        assert isinstance(outs[1], LogicError)
        assert eng.stats["ingest_errors"] == 1
        for j, q in ((0, _X[:3]), (2, _X[5:9])):
            np.testing.assert_array_equal(outs[j][1], _solo(_X, q)[1])

    def test_exhausted_retries_surface_typed_and_engine_recovers(self):
        eng = _engine(max_retries=1)
        with faults.plan("dispatch:times=0:raise"):
            outs = eng.search([_X[:3], _X[5:9]])
        assert all(isinstance(o, faults.InjectedFault) for o in outs)
        assert eng.stats["dispatch_errors"] >= 1
        outs = eng.search([_X[:3]])
        np.testing.assert_array_equal(outs[0][1], _solo(_X, _X[:3])[1])


# ---------------------------------------------------------------------------
# admission: deadlines, shedding, bounded queue, expiry


class TestAdmission:
    def test_deadline_shed_at_admission_typed(self):
        adm = AdmissionController(policy="shed-over-deadline",
                                  static_batch_s=10.0, use_telemetry=False)
        eng = ServeEngine(_X, _K, max_batch=16, admission=adm)
        eng.warmup()
        reqs = [ServeRequest(_X[:10], timeout_s=100.0),
                ServeRequest(_X[:10], timeout_s=1.0)]
        outs = eng.search(reqs)
        np.testing.assert_array_equal(outs[0][1], _solo(_X, _X[:10])[1])
        assert isinstance(outs[1], RejectedError)
        assert outs[1].reason == "deadline"
        assert eng.stats["sheds"] == 1 and eng.stats["admitted"] == 1
        health = eng._health()
        assert health["ready"] and health["degraded"]
        assert health["admission"]["shed_total"] == 1

    def test_overload_keeps_admitted_latency_bounded(self):
        """The shed-under-overload property at unit scale: with a deadline
        budget over an offered load the engine cannot clear in budget, the
        excess is shed and every ADMITTED request completes within the
        budget (+ slack) — the bench drives the full 2x-load version."""
        adm = AdmissionController(policy="shed-over-deadline",
                                  static_batch_s=0.004,
                                  use_telemetry=False)
        eng = ServeEngine(_X, _K, max_batch=16, admission=adm)
        eng.warmup()
        eng.search([_X[:2]])
        budget = 0.02
        reqs = [ServeRequest(_X[j * 10:j * 10 + 10], timeout_s=budget)
                for j in range(12)]  # 12 batches projected ≫ budget
        outs = eng.search(reqs)
        served = [j for j, o in enumerate(outs) if isinstance(o, tuple)]
        shed = [o for o in outs if isinstance(o, RejectedError)]
        assert shed, "2x-over-budget load shed nothing"
        assert served, "admission shed everything"
        lats = [eng.last_latencies[j] for j in served]
        assert max(lats) <= budget + 0.25, \
            f"admitted p-max latency {max(lats):.3f}s not bounded"
        for j in served:
            np.testing.assert_array_equal(
                outs[j][1], _solo(_X, _X[j * 10:j * 10 + 10])[1])

    def test_bounded_queue_sheds_newest(self):
        adm = AdmissionController(policy="shed-newest", max_queue=20,
                                  use_telemetry=False)
        eng = ServeEngine(_X, _K, max_batch=64, admission=adm)
        eng.warmup()
        outs = eng.search([_X[:15], _X[20:30], _X[40:43]])
        assert isinstance(outs[0], tuple)
        assert isinstance(outs[1], RejectedError)
        assert outs[1].reason == "overload"
        # 15 + 3 fits back under the bound: the queue drains per-request
        assert isinstance(outs[2], tuple)
        np.testing.assert_array_equal(outs[2][1], _solo(_X, _X[40:43])[1])

    def test_admitted_but_expired_dropped_at_dispatch(self):
        """shed-over-deadline's dispatch-time pass: an admitted request
        whose deadline passed before its super-batch assembled is dropped
        with reason='expired', not dispatched late."""
        adm = AdmissionController(policy="shed-over-deadline",
                                  static_batch_s=0.0, use_telemetry=False)
        eng = ServeEngine(_X, _K, max_batch=16, admission=adm)
        eng.warmup()
        reqs = [ServeRequest(_X[:16], timeout_s=100.0),
                ServeRequest(_X[20:24], timeout_s=0.0)]  # admits (est 0)
        outs = eng.search(reqs)
        np.testing.assert_array_equal(outs[0][1], _solo(_X, _X[:16])[1])
        assert isinstance(outs[1], RejectedError)
        assert outs[1].reason == "expired"
        assert eng.stats["expired"] == 1

    def test_shed_newest_serves_expired_late_but_counts(self):
        adm = AdmissionController(policy="shed-newest",
                                  static_batch_s=0.0, use_telemetry=False)
        eng = ServeEngine(_X, _K, max_batch=16, admission=adm)
        eng.warmup()
        reqs = [ServeRequest(_X[:16], timeout_s=100.0),
                ServeRequest(_X[20:24], timeout_s=0.0)]
        outs = eng.search(reqs)
        # admission is a promise under shed-newest: served late, counted
        np.testing.assert_array_equal(outs[1][1],
                                      _solo(_X, _X[20:24])[1])
        assert eng.stats["expired"] == 1

    def test_serve_request_without_deadline_is_plain(self):
        eng = _engine()
        outs = eng.search([ServeRequest(_X[:5]), _X[:5]])
        np.testing.assert_array_equal(outs[0][1], outs[1][1])
        np.testing.assert_array_equal(outs[0][0], outs[1][0])

    def test_admission_counters_exported(self):
        from raft_tpu import telemetry

        snap = telemetry.snapshot()
        assert "raft_tpu_serve_shed_total" in snap
        assert "raft_tpu_serve_admitted_total" in snap
        assert "raft_tpu_serve_expired_total" in snap


# ---------------------------------------------------------------------------
# refresh atomicity + concurrency under the fault plane


class TestRefreshAtomicity:
    def test_crashed_refresh_leaves_old_backend_serving(self):
        """The acceptance gate: a crash injected BETWEEN re-lower and swap
        leaves the OLD backend fully serving, bit-identically — proven
        under injected crash, not by code reading."""
        eng = _engine()
        with faults.plan("refresh:stage=pre_swap:raise"):
            with pytest.raises(faults.InjectedFault):
                eng.refresh(_X2)
        assert eng.stats["refreshes"] == 0
        health = eng._health()
        assert health["ready"] and not health["refresh_in_flight"]
        outs = eng.search([_X[:6]])
        np.testing.assert_array_equal(outs[0][1], _solo(_X, _X[:6])[1])
        np.testing.assert_array_equal(outs[0][0], _solo(_X, _X[:6])[0])
        # and a later clean refresh still lands the new index
        eng.refresh(_X2)
        outs = eng.search([_X[:6]])
        np.testing.assert_array_equal(outs[0][1], _solo(_X2, _X[:6])[1])

    def test_pre_warm_crash_equally_atomic(self):
        eng = _engine()
        with faults.plan("refresh:stage=pre_warm:raise"):
            with pytest.raises(faults.InjectedFault):
                eng.refresh(_X2)
        outs = eng.search([_X[:4]])
        np.testing.assert_array_equal(outs[0][1], _solo(_X, _X[:4])[1])

    def test_concurrent_refresh_and_search_single_generation(self):
        """Hammer search() across an injected SLOW swap: every response
        comes bit-identical from exactly ONE backend generation (old or
        new, never a mix), `_refreshing` gates /healthz, and post-swap
        traffic is all new-generation."""
        eng = _engine()
        q = _X[:7]
        d_old, i_old = _solo(_X, q)
        d_new, i_new = _solo(_X2, q)
        assert not np.array_equal(i_old, i_new), "degenerate test data"
        saw_refreshing = []
        errors = []

        def do_refresh():
            try:
                with faults.plan("refresh:stage=pre_swap:stall=0.4"):
                    eng.refresh(_X2)
            except Exception as e:  # surfaced below
                errors.append(e)

        t = threading.Thread(target=do_refresh)
        t.start()
        generations = set()
        while t.is_alive():
            health = eng._health()
            if health["refresh_in_flight"]:
                saw_refreshing.append(health["ready"])
            (d, i), = eng.search([q])
            if np.array_equal(i, i_old) and np.array_equal(d, d_old):
                generations.add("old")
            elif np.array_equal(i, i_new) and np.array_equal(d, d_new):
                generations.add("new")
            else:
                generations.add("MIXED")
        t.join(30)
        assert not errors, errors
        assert "MIXED" not in generations, \
            "a response matched neither backend generation bitwise"
        assert saw_refreshing and not any(saw_refreshing), \
            "/healthz stayed ready during the injected slow swap"
        (d, i), = eng.search([q])  # post-swap: new generation only
        np.testing.assert_array_equal(i, i_new)


# ---------------------------------------------------------------------------
# bounded, idempotent shutdown


class TestClose:
    def test_close_idempotent_and_rejects_typed(self):
        eng = _engine()
        eng.close()
        eng.close()  # double-close is a no-op
        with pytest.raises(RejectedError) as exc:
            eng.search([_X[:2]])
        assert exc.value.reason == "closed"
        with pytest.raises(LogicError):
            eng.warmup()
        with pytest.raises(LogicError):
            eng.refresh(_X2)
        assert eng._health()["ready"] is False

    def test_close_drains_in_flight_requests(self):
        eng = _engine()
        outs = {}

        def slow_search():
            with faults.plan("dispatch:n=1:stall=0.4"):
                outs["v"] = eng.search([_X[:3]])

        t = threading.Thread(target=slow_search)
        t.start()
        time.sleep(0.1)  # let the search take the engine lock
        t0 = time.monotonic()
        eng.close(timeout_s=5.0)
        close_wall = time.monotonic() - t0
        t.join(10)
        # close returned only after the in-flight call drained, and the
        # drained call's results are intact
        np.testing.assert_array_equal(outs["v"][0][1],
                                      _solo(_X, _X[:3])[1])
        assert close_wall < 5.0
        with pytest.raises(RejectedError):
            eng.search([_X[:2]])

    def test_close_stops_scrape_server(self):
        import urllib.error
        import urllib.request

        eng = _engine()
        srv = eng.serve_http(port=0)
        url = f"{srv.url}/healthz"
        with urllib.request.urlopen(url, timeout=5) as r:
            assert json.loads(r.read())["ready"] is True
        eng.close()
        with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
            urllib.request.urlopen(url, timeout=1)


# ---------------------------------------------------------------------------
# the trace-time guarantee: the plane adds NOTHING to lowered programs


@contextlib.contextmanager
def _x64_off():
    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", False)
    try:
        yield
    finally:
        jax.config.update("jax_enable_x64", prev)


class TestTracePurity:
    def test_installed_plan_leaves_fingerprints_byte_identical(self):
        """Lower a registered serve program with a dispatch/refresh plan
        INSTALLED and with the plane off: the structural fingerprints
        serialize byte-identically, and both diff clean against the
        committed golden (the full 13-golden pass is CI's job)."""
        from raft_tpu.analysis import fingerprint, registry

        entry = registry.get_program("brute_force.knn_scan")
        with _x64_off():
            fp_off = fingerprint.extract(entry)
            with faults.plan("dispatch:n=1:raise;dispatch:n=2:stall=9;"
                             "refresh:stage=pre_swap:raise"):
                fp_on = fingerprint.extract(entry)
        assert fingerprint.dumps(fp_off) == fingerprint.dumps(fp_on)
        golden = json.loads(
            fingerprint.golden_path(entry.name).read_text())
        assert fingerprint.diff(golden, fp_off) == []

    def test_hooks_are_free_when_off(self):
        # the whole plane reduces to one attribute read per hook site
        assert faults.active_plan() is None
        faults.check("dispatch")
        faults.check("comms", rank=0, op="isend")
        faults.check("refresh", stage="pre_swap")
