"""Replica routing battery (ISSUE 15, docs/sharded_ann.md §replica
groups): the 2D (shard × replica) carve via ``Comms.replica_split``,
replica-group ``ShardedIndex`` construction, routed serving with the
degrade path, per-group collective byte accounting, MeshAot cache-key
isolation across groups, fleet-telemetry rollup of per-replica rows, and
the AOT executable store over mesh programs."""

import numpy as np
import pytest

from raft_tpu import telemetry
from raft_tpu.comms import build_comms
from raft_tpu.core.aot import aot_compile_counters
from raft_tpu.core.error import RaftError
from raft_tpu.neighbors import ann_mnmg, brute_force, ivf_flat
from raft_tpu.serve import ServeEngine
from raft_tpu.testing import faults

_DIM = 16
_K = 4


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(0)
    return rng.random((2048, _DIM), dtype=np.float32)


@pytest.fixture(scope="module")
def fl_index(corpus):
    return ivf_flat.build(
        ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4), corpus)


@pytest.fixture(scope="module")
def replica_set(fl_index):
    return ann_mnmg.replicate(fl_index, build_comms(), 2)


def _reqs(seed=1, sizes=(3, 7, 2, 9, 1, 5)):
    rng = np.random.default_rng(seed)
    return [rng.random((n, _DIM), dtype=np.float32) for n in sizes]


_SP = ivf_flat.SearchParams(n_probes=3)


class TestReplicaSplit:
    def test_layout_carves_contiguous_groups(self):
        comms = build_comms()
        lay = comms.replica_split(2)
        assert lay.n_replicas == 2 and lay.group_size == 4
        assert lay.split.groups == [[0, 1, 2, 3], [4, 5, 6, 7]]
        for r, g in enumerate(lay.groups):
            devs = list(g.mesh.devices.flat)
            assert len(devs) == 4
            assert g.groups is None  # full-axis within its sub-mesh
        # the two views are one carve: split's group r == group r's devices
        all_devs = list(comms.mesh.devices.flat)
        for r, g in enumerate(lay.groups):
            assert list(g.mesh.devices.flat) \
                == all_devs[r * 4:(r + 1) * 4]

    def test_invalid_splits_raise(self):
        comms = build_comms()
        with pytest.raises(RaftError):
            comms.replica_split(3)  # 8 % 3 != 0
        lay = comms.replica_split(2)
        with pytest.raises(RaftError):
            lay.split.replica_split(2)  # no re-splitting a split comm

    def test_per_group_collective_isolation(self):
        comms = build_comms()
        lay = comms.replica_split(2)
        g0, g1 = lay.groups
        before1 = dict(g1.collective_calls)
        g0.run(lambda x: g0.allreduce(x), np.ones((4, 2), np.float32))
        assert g0.collective_calls["allreduce"] == 1
        assert g0.collective_calls["allreduce_bytes"] == 8
        # group 1's registry rows did not move: per-instance comm labels
        assert dict(g1.collective_calls) == before1


class TestReplicate:
    def test_each_group_matches_local_search(self, fl_index, replica_set,
                                             corpus):
        q = _reqs()[3]
        d_l, i_l = ivf_flat.search(_SP, fl_index, q, _K)
        for r in range(replica_set.n_replicas):
            d, i = ann_mnmg.search(replica_set.replicas[r], q, _K, _SP)
            assert np.array_equal(np.asarray(i), np.asarray(i_l))
            assert np.array_equal(np.asarray(d), np.asarray(d_l))

    def test_layout_reuse_and_arg_validation(self, fl_index):
        comms = build_comms()
        lay = comms.replica_split(2)
        rep = ann_mnmg.replicate(fl_index, lay)
        assert rep.n_replicas == 2
        with pytest.raises(RaftError):
            ann_mnmg.replicate(fl_index, lay, 4)  # disagrees with layout
        with pytest.raises(RaftError):
            ann_mnmg.replicate(fl_index, comms)  # n_replicas required

    def test_no_meshaot_cache_aliasing_across_groups(self, replica_set):
        # a split comm must round-trip through the MeshAot cache keys:
        # each group's searcher binds its OWN program (cached on its own
        # communicator), so warming one group cannot silently satisfy —
        # or poison — the other group's signatures
        s0 = replica_set.replicas[0].searcher(_K, _SP)
        s1 = replica_set.replicas[1].searcher(_K, _SP)
        assert s0.fn is not s1.fn
        # same statics on the SAME group → the same cached program
        s0b = replica_set.replicas[0].searcher(_K, _SP)
        assert s0.fn is s0b.fn
        import jax.numpy as jnp

        s0.warm(8, jnp.float32)
        c0 = aot_compile_counters["compiles"]
        s0.warm(8, jnp.float32)  # cache hit within the group
        assert aot_compile_counters["compiles"] == c0
        s1.warm(8, jnp.float32)  # the OTHER group must lower its own
        assert aot_compile_counters["compiles"] > c0


class TestReplicaServe:
    def test_routed_identical_zero_compile_per_group_allgather(
            self, fl_index, replica_set):
        eng = ServeEngine(replica_set, _K, _SP, max_batch=16)
        eng.warmup()
        reqs = _reqs(seed=2)
        eng.search(reqs[:1])  # plumbing warm call
        g_counts = [dict(g.collective_calls)
                    for g in replica_set.layout.groups]
        # warm time staged every launch: exactly one allgather per traced
        # (bucket) program per group, group-world payload
        for counts in g_counts:
            assert counts.get("allgather", 0) >= 1
            assert counts.get("allgather_bytes", 0) > 0
        c0 = aot_compile_counters["compiles"]
        outs = eng.search(reqs)
        assert aot_compile_counters["compiles"] == c0
        for q, (d, i) in zip(reqs, outs):
            d_l, i_l = ivf_flat.search(_SP, fl_index, q, _K)
            assert np.array_equal(i, np.asarray(i_l))
            assert np.array_equal(d, np.asarray(d_l))
        # steady-state serving traced nothing new: the per-group
        # trace-time counters are EXACTLY what warmup left
        assert [dict(g.collective_calls)
                for g in replica_set.layout.groups] == g_counts
        # the router actually spread batches across both lanes
        disp = telemetry.REGISTRY.get("raft_tpu_serve_replica_dispatch_total")
        lanes_used = {labels[1] for labels, v in disp.items()
                      if labels[0] == eng._engine_id and v > 0}
        assert lanes_used == {"0", "1"}
        eng.close()

    def test_degrade_reroutes_zero_failures_healthz(self, fl_index,
                                                    replica_set):
        eng = ServeEngine(replica_set, _K, _SP, max_batch=16)
        eng.warmup()
        reqs = _reqs(seed=3)
        eng.search(reqs[:1])
        c0 = aot_compile_counters["compiles"]
        # lane 0 (the router's first pick) faults on EVERY dispatch:
        # traffic must drain to lane 1 with zero failed requests
        with faults.plan("comms:op=replica_dispatch:rank=0:raise"):
            outs = eng.search(reqs)
        assert aot_compile_counters["compiles"] == c0  # reroute warmed
        assert all(isinstance(o, tuple) for o in outs)
        for q, (d, i) in zip(reqs, outs):
            _, i_l = ivf_flat.search(_SP, fl_index, q, _K)
            assert np.array_equal(i, np.asarray(i_l))
        assert eng.stats["replica_faults"] >= 1
        assert eng.stats["replica_reroutes"] >= 1
        body = eng._health()
        assert body["degraded"] is True
        assert body["replicas"] == {"total": 2, "live": 1,
                                    "degraded": [0]}
        # the drain is sticky after the plan clears (a faulted replica
        # stays out until an operator restores or refreshes)
        outs2 = eng.search(reqs[:2])
        assert all(isinstance(o, tuple) for o in outs2)
        assert eng._health()["replicas"]["degraded"] == [0]
        eng._router.restore(0)
        assert eng._health()["replicas"]["degraded"] == []
        eng.close()

    def test_injected_logic_fault_fails_fast(self, replica_set):
        # a LOGIC fault (shape/dtype-bug family) must NOT drain-and-
        # reroute — that would mask a deterministic bug as lane loss
        eng = ServeEngine(replica_set, _K, _SP, max_batch=16)
        eng.warmup()
        eng.search(_reqs(seed=4)[:1])
        with faults.plan("comms:op=replica_dispatch:rank=0:raise=logic"):
            outs = eng.search(_reqs(seed=4)[:1])
        assert isinstance(outs[0], Exception)
        assert eng._health()["replicas"]["degraded"] == []
        eng.close()

    @pytest.mark.slow  # tier-1 budget (ISSUE-20 rebalance): flat/pq
    # replica cells carry the 2D routing contract; brute-force serve
    # identity is covered by the coalescing battery
    def test_brute_force_replicas(self, corpus):
        rep = ann_mnmg.replicate(corpus, build_comms(), 2)
        assert rep.kind == "brute_force"
        eng = ServeEngine(rep, _K, max_batch=16)
        eng.warmup()
        reqs = _reqs(seed=5, sizes=(3, 6, 2))
        outs = eng.search(reqs)
        for q, (d, i) in zip(reqs, outs):
            _, i_l = brute_force.knn(corpus, q, _K)
            assert np.array_equal(i, np.asarray(i_l))
        # oversize → solo through one replica group, still identical
        big = _reqs(seed=6, sizes=(25,))[0]
        (d, i), = eng.search([big])
        _, i_l = brute_force.knn(corpus, big, _K)
        assert np.array_equal(i, np.asarray(i_l))
        assert eng.stats["solo_fallbacks"] == 1
        eng.close()


class TestFleetRollup:
    def test_gather_rolls_up_per_replica_rows_without_collisions(
            self, replica_set):
        # every group communicator's byte/count rows ride the snapshot
        # under its own comm= ordinal — the parent-comms gather rollup
        # must carry each group's view exactly (no label collisions
        # folding two groups into one row)
        for g in replica_set.layout.groups:
            assert dict(g.collective_calls), "fixture groups have traffic"
        fleet = telemetry.gather(replica_set.layout.parent)
        roll = fleet["rollup"].get(
            "raft_tpu_comms_collective_calls", {}).get("values", {})
        prefixes = set()
        for g in replica_set.layout.groups:
            prefix = ",".join(
                f"comm={v}" for v in g.collective_calls.fixed_labels)
            prefixes.add(prefix)
            for key, val in dict(g.collective_calls).items():
                assert roll.get(f"{prefix},key={key}") == val, (prefix,
                                                                key)
        assert len(prefixes) == len(replica_set.layout.groups)

    def test_merge_sums_counter_rows_additively(self):
        from raft_tpu.telemetry import aggregate

        snap = telemetry.snapshot()
        name = "raft_tpu_comms_collective_calls"
        if name not in snap:
            pytest.skip("no comms rows in this process")
        merged = aggregate.merge([snap, snap])
        for key, val in snap[name]["values"].items():
            assert merged[name]["values"][key] == 2 * val


class TestExecutableStoreMeshPrograms:
    def test_store_round_trips_replica_group_executable(self, tmp_path,
                                                        replica_set):
        # the cold-start satellite must cover the (bucket, dtype, world)
        # MESH signatures too: serialize one group's warmed shard_map
        # executable, clear the in-process cache, and restore with zero
        # XLA compiles — results bit-identical
        import jax.numpy as jnp

        from raft_tpu.core import aotstore

        searchers = [r.searcher(_K, _SP) for r in replica_set.replicas]
        q = _reqs(seed=7, sizes=(8,))[0]
        prev = aotstore.install(str(tmp_path))
        try:
            for s in searchers:
                s.fn._cache.clear()  # force store-visible misses
                s.warm(8, jnp.float32)
            # one entry PER GROUP: congruent sub-meshes repr identically,
            # so the store key must carry the device assignment — a
            # collision here loads group 0's executable onto group 1's
            # devices (the aliasing bug the verify drive caught)
            import os as _os

            assert len(_os.listdir(str(tmp_path))) == len(searchers)
            base = [s.dispatch(q) for s in searchers]
            for s in searchers:
                s.fn._cache.clear()  # simulate the process restart
            h0 = aot_compile_counters["store_hits"]
            c0 = aot_compile_counters["compiles"]
            for s in searchers:
                s.warm(8, jnp.float32)
            assert aot_compile_counters["compiles"] == c0
            assert aot_compile_counters["store_hits"] == h0 + len(searchers)
            for s, (d0, i0) in zip(searchers, base):
                d1, i1 = s.dispatch(q)
                assert np.array_equal(np.asarray(i0), np.asarray(i1))
                assert np.array_equal(np.asarray(d0), np.asarray(d1))
        finally:
            aotstore.install(prev)
