"""Continuous-batching scheduler battery (ISSUE 15, docs/serving.md
§scheduler): the telemetry-steered chooser, the streaming quantum rule,
the replica router, and the cost model — plus the merged-quantile
telemetry helper they seed from."""

import threading
import time

import numpy as np
import pytest

from raft_tpu import telemetry
from raft_tpu.core.aot import _bucket_dim, aot_compile_counters
from raft_tpu.neighbors.brute_force import knn
from raft_tpu.serve import (RejectedError, SchedulerConfig, ServeEngine,
                            ServeRequest)
from raft_tpu.serve import schedule

_DIM = 16
_K = 4


def _data(n=512, seed=0):
    rng = np.random.default_rng(seed)
    return rng.random((n, _DIM), dtype=np.float32)


def _bucket_for(total, max_batch=1024, warmed=frozenset()):
    b = min(_bucket_dim(total), max_batch)
    if warmed and b not in warmed:
        bigger = [w for w in warmed if w >= total]
        if bigger:
            b = min(bigger)
    return b


def _drain_all(sizes, max_bucket):
    """The legacy drain-all packing (ServeEngine._plan), as the oracle."""
    batches, solo, cur, cur_n = [], [], [], 0
    for j, n in enumerate(sizes):
        if n > max_bucket:
            solo.append(j)
            continue
        if cur_n + n > max_bucket:
            batches.append(cur)
            cur, cur_n = [], 0
        cur.append((j, cur_n, n))
        cur_n += n
    if cur:
        batches.append(cur)
    return batches, solo


class TestChooser:
    def test_flat_cost_reproduces_drain_all(self):
        # cold (flat) cost model: minimizing total cost minimizes the
        # batch count, which IS the drain-all packing — the shipped
        # default changes nothing until telemetry says otherwise
        rng = np.random.default_rng(1)
        cm = schedule.CostModel(use_telemetry=False)
        for _ in range(100):
            sizes = [int(s) for s in rng.choice(
                [1, 2, 5, 8, 16, 40, 130, 700, 1100],
                size=rng.integers(0, 30))]
            dls = [None] * len(sizes)
            b1, s1 = schedule.choose_batches(
                sizes, dls, _bucket_for, 1024, cm, "float32", 0.0)
            b2, s2 = _drain_all(sizes, 1024)
            assert s1 == s2
            assert len(b1) == len(b2), (sizes, b1, b2)
            assert [m[0] for b in b1 for m in b] \
                == [m[0] for b in b2 for m in b]
            # members stay in arrival order with contiguous row offsets
            for batch in b1:
                start = 0
                for _j, st, n in batch:
                    assert st == start
                    start += n

    def test_measured_costs_can_split_batches(self):
        # a measured cost surface where padding 512+16 rows to one 1024
        # bucket costs far more than dispatching 512 + 8 separately:
        # the chooser must split — and every bucket it uses must come
        # from the ladder callable (never a raw total)
        cm = schedule.CostModel()
        cm.observe("float32", 8, 0.001)
        cm.observe("float32", 1024, 0.100)
        seen = []

        def ladder(total):
            b = _bucket_for(total)
            seen.append(b)
            return b

        batches, solo = schedule.choose_batches(
            [512, 16], [None, None], ladder, 1024, cm, "float32", 0.0)
        assert solo == []
        assert [[m[0] for m in b] for b in batches] == [[0], [1]]
        assert all(b == _bucket_dim(b) for b in seen)

    def test_deadline_pressure_breaks_ties(self):
        # cost(16) == cost(8) + cost(8) exactly — a packing tie; the
        # first request's tight deadline must pull it into its own
        # earlier-completing batch
        cm = schedule.CostModel()
        cm.observe("float32", 8, 0.05)
        cm.observe("float32", 16, 0.10)
        batches, _ = schedule.choose_batches(
            [8, 8], [0.06, None], _bucket_for, 1024, cm, "float32", 0.0)
        assert [[m[0] for m in b] for b in batches] == [[0], [1]]
        # without the deadline the tie resolves to either packing but
        # never to a THIRD, costlier plan
        batches2, _ = schedule.choose_batches(
            [8, 8], [None, None], _bucket_for, 1024, cm, "float32", 0.0)
        assert len(batches2) in (1, 2)

    def test_oversize_requests_go_solo(self):
        cm = schedule.CostModel(use_telemetry=False)
        batches, solo = schedule.choose_batches(
            [4, 2000, 3], [None] * 3, _bucket_for, 1024, cm,
            "float32", 0.0)
        assert solo == [1]
        assert [m[0] for b in batches for m in b] == [0, 2]


class TestCostModel:
    def test_precedence_static_then_observed(self):
        cm = schedule.CostModel(static_batch_s=0.25, use_telemetry=False)
        assert cm.batch_cost_s("float32", 64) == 0.25
        cm2 = schedule.CostModel(static_batch_s=0.25)
        assert cm2.batch_cost_s("float32", 64) == 0.25  # cold, no fn
        cm2.observe("float32", 64, 0.01)
        assert cm2.batch_cost_s("float32", 64) == pytest.approx(0.01)
        # EWMA folds subsequent observations
        cm2.observe("float32", 64, 0.02)
        assert 0.01 < cm2.batch_cost_s("float32", 64) < 0.02

    def test_bucket_interpolation(self):
        cm = schedule.CostModel()
        cm.observe("float32", 8, 0.010)
        cm.observe("float32", 128, 0.070)
        # fixed+per-row decomposition: 8→0.01, 128→0.07 ⇒ per-row 5e-4,
        # fixed 6e-3 ⇒ 64 → 0.038
        assert cm.batch_cost_s("float32", 64) == pytest.approx(0.038,
                                                               rel=1e-6)
        # dtypes do not bleed into each other
        assert cm.batch_cost_s("bfloat16", 64) == cm.static_batch_s

    def test_registry_seed_via_merged_quantile(self):
        # the (fn, sig)-labeled dispatch histogram seeds a per-fn cost:
        # rows merge bucket-wise (telemetry.registry.merged_quantile)
        hist = telemetry.histogram(
            "raft_tpu_aot_dispatch_seconds",
            "host-side dispatch latency", labelnames=("fn", "sig"))
        fn = "test_sched_seed_fn"
        for v in (0.02, 0.02, 0.02):
            hist.observe(v, (fn, "aaaa"))
        for v in (0.02, 0.02):
            hist.observe(v, (fn, "bbbb"))
        cm = schedule.CostModel(fn=fn)
        est = cm.batch_cost_s("float32", 32)
        assert est == pytest.approx(0.02, rel=0.5)  # one bucket ratio

    def test_merged_quantile_prefix_isolation(self):
        from raft_tpu.telemetry.registry import merged_quantile

        hist = telemetry.histogram(
            "test_merged_quantile_hist", "x", labelnames=("fn", "sig"))
        hist.observe(0.001, ("a", "s1"))
        hist.observe(0.001, ("a", "s2"))
        hist.observe(10.0, ("b", "s1"))
        got = merged_quantile(hist, 0.5, ("a",))
        assert got is not None and got < 0.01  # b's rows must not bleed
        assert merged_quantile(hist, 0.5, ("c",)) is None


class TestShouldDispatch:
    def test_rules(self):
        q = 0.010
        # empty queue never dispatches
        assert not schedule.should_dispatch(0, 64, 1.0, q, [], 0.0, 0.01)
        # fills the largest warmed bucket → now
        assert schedule.should_dispatch(64, 64, 0.0, q, [], 0.0, 0.01)
        # fresh partial batch → wait one quantum
        assert not schedule.should_dispatch(8, 64, 0.001, q, [], 0.0,
                                            0.01)
        # oldest member waited a full quantum → now
        assert schedule.should_dispatch(8, 64, 0.02, q, [], 0.0, 0.01)
        # a deadline that one more quantum would jeopardize → now
        assert schedule.should_dispatch(8, 64, 0.0, q, [0.015], 0.0, 0.01)
        # a comfortable deadline → still wait
        assert not schedule.should_dispatch(8, 64, 0.0, q, [10.0], 0.0,
                                            0.01)


class TestReplicaRouter:
    def test_least_loaded_spread_and_drain(self):
        r = schedule.ReplicaRouter(2, "test-router")
        # equal horizons: consecutive picks alternate lanes
        l0 = r.pick(0.0, 1.0)
        l1 = r.pick(0.0, 1.0)
        assert {l0, l1} == {0, 1}
        # the busier lane loses the next pick
        r.note_done(l0, 0.0)
        assert r.pick(0.0, 0.1) == l0
        # fault drains: all traffic lands on the survivor
        r.fault(0)
        assert r.alive_lanes() == [1]
        assert all(r.pick(0.0, 0.1) == 1 for _ in range(4))
        assert r.health() == {"total": 2, "live": 1, "degraded": [0]}
        # exclusion on top of draining → nothing left
        assert r.pick(0.0, 0.1, exclude=[1]) is None
        r.restore(0)
        assert r.health()["live"] == 2


class TestEngineScheduler:
    def test_scheduler_on_off_bit_identical_zero_compile(self):
        x = _data()
        rng = np.random.default_rng(3)
        reqs = [rng.random((n, _DIM), dtype=np.float32)
                for n in (3, 9, 1, 14, 6, 2)]
        eng_on = ServeEngine(x, _K, max_batch=32)
        eng_off = ServeEngine(x, _K, max_batch=32, scheduler=False)
        for e in (eng_on, eng_off):
            e.warmup()
            e.search(reqs[:1])
        c0 = aot_compile_counters["compiles"]
        outs_on = eng_on.search(reqs)
        outs_off = eng_off.search(reqs)
        assert aot_compile_counters["compiles"] == c0
        for q, (d1, i1), (d2, i2) in zip(reqs, outs_on, outs_off):
            d_l, i_l = knn(x, q, _K)
            assert np.array_equal(i1, np.asarray(i_l))
            assert np.array_equal(i2, np.asarray(i_l))
            assert np.array_equal(d1, d2)

    def test_chooser_uses_only_warmed_buckets_after_observations(self):
        # drive per-bucket EWMAs to a pathological surface, then serve a
        # stream: whatever packing the chooser picks, the zero-compile
        # counter proves every bucket was pre-lowered
        x = _data()
        eng = ServeEngine(x, _K, max_batch=64)
        eng.warmup()
        eng._cost.observe("float32", 8, 0.0001)
        eng._cost.observe("float32", 64, 1.0)
        rng = np.random.default_rng(4)
        reqs = [rng.random((n, _DIM), dtype=np.float32)
                for n in (30, 5, 3, 20, 8)]
        eng.search([reqs[0]])
        c0 = aot_compile_counters["compiles"]
        outs = eng.search(reqs)
        assert aot_compile_counters["compiles"] == c0
        for q, (d, i) in zip(reqs, outs):
            _, i_l = knn(x, q, _K)
            assert np.array_equal(i, np.asarray(i_l))
        # the skewed surface makes big buckets expensive → more, smaller
        # batches than drain-all's single fill
        assert eng.stats["super_batches"] >= 3

    def test_submit_streaming_coalesces_and_matches(self):
        x = _data()
        eng = ServeEngine(x, _K, max_batch=32,
                          scheduler=SchedulerConfig(quantum_s=0.02))
        eng.warmup()
        eng.search([_data(2, seed=9)])  # plumbing warm
        rng = np.random.default_rng(5)
        reqs = [rng.random((n, _DIM), dtype=np.float32)
                for n in (2, 3, 4, 1, 5)]
        sb0 = eng.stats["super_batches"]
        c0 = aot_compile_counters["compiles"]
        futs = [eng.submit(q) for q in reqs]
        outs = [f.result(timeout=30) for f in futs]
        assert aot_compile_counters["compiles"] == c0
        for q, (d, i) in zip(reqs, outs):
            _, i_l = knn(x, q, _K)
            assert np.array_equal(i, np.asarray(i_l))
        # the quantum coalesced concurrent submissions: fewer batches
        # than requests (15 rows fit one 16-bucket)
        assert eng.stats["super_batches"] - sb0 < len(reqs)
        assert eng.stats["sched_dispatches"] >= 1
        eng.close()

    def test_submit_deadline_rides_through_admission(self):
        x = _data()
        eng = ServeEngine(x, _K, max_batch=32,
                          scheduler=SchedulerConfig(quantum_s=0.01))
        eng.warmup()
        fut = eng.submit(ServeRequest(_data(3, seed=11),
                                      deadline_s=telemetry.now() - 1.0))
        eng.flush()
        with pytest.raises(RejectedError):
            fut.result(timeout=30)
        eng.close()

    def test_submit_after_close_rejects_and_pending_resolve(self):
        x = _data()
        eng = ServeEngine(x, _K, max_batch=32,
                          scheduler=SchedulerConfig(quantum_s=30.0))
        eng.warmup()
        fut = eng.submit(_data(2, seed=12))  # parked behind a huge quantum
        eng.close()
        with pytest.raises(RejectedError):
            fut.result(timeout=30)
        with pytest.raises(RejectedError):
            eng.submit(_data(2, seed=12))

    def test_submit_requires_scheduler(self):
        eng = ServeEngine(_data(), _K, max_batch=32, scheduler=False)
        with pytest.raises(Exception):
            eng.submit(_data(2, seed=13))
        eng.close()

    def test_concurrent_submitters_one_batch(self):
        # several threads submit within one quantum: the scheduler thread
        # must coalesce them and every future must resolve correctly
        x = _data()
        eng = ServeEngine(x, _K, max_batch=64,
                          scheduler=SchedulerConfig(quantum_s=0.05))
        eng.warmup()
        eng.search([_data(2, seed=14)])
        rng = np.random.default_rng(6)
        reqs = [rng.random((3, _DIM), dtype=np.float32) for _ in range(8)]
        futs = [None] * len(reqs)

        def worker(j):
            futs[j] = eng.submit(reqs[j])

        threads = [threading.Thread(target=worker, args=(j,))
                   for j in range(len(reqs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        t0 = time.monotonic()
        outs = [f.result(timeout=30) for f in futs]
        assert time.monotonic() - t0 < 25
        for q, (d, i) in zip(reqs, outs):
            _, i_l = knn(x, q, _K)
            assert np.array_equal(i, np.asarray(i_l))
        eng.close()
