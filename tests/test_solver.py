"""LAP auction solver vs scipy.optimize.linear_sum_assignment oracle."""

import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

from raft_tpu.solver import LinearAssignmentProblem, solve_lap


def scipy_objective(cost):
    r, c = linear_sum_assignment(cost)
    return cost[r, c].sum()


@pytest.mark.parametrize("n,seed", [(8, 0), (32, 1), (64, 2)])
def test_lap_float_optimal(n, seed):
    rng = np.random.default_rng(seed)
    cost = rng.random((n, n)).astype(np.float32)
    res = solve_lap(cost, epsilon=1e-7)
    r2c = np.array(res.row_assignment)
    # valid permutation
    assert sorted(r2c.tolist()) == list(range(n))
    # col_assignment is the inverse permutation
    c2r = np.array(res.col_assignment)
    assert np.array_equal(c2r[r2c], np.arange(n))
    # within n·eps of scipy's optimum
    ref = scipy_objective(cost.astype(np.float64))
    assert float(res.objective) <= ref + n * 1e-5
    np.testing.assert_allclose(float(res.objective),
                               cost[np.arange(n), r2c].sum(), rtol=1e-5)


def test_lap_integer_exact():
    rng = np.random.default_rng(3)
    n = 24
    cost = rng.integers(0, 100, (n, n)).astype(np.float32)
    res = solve_lap(cost, epsilon=1.0 / (2 * n))
    r2c = np.array(res.row_assignment)
    assert sorted(r2c.tolist()) == list(range(n))
    # integer costs + eps < 1/n → provably exact optimum
    assert float(res.objective) == scipy_objective(cost.astype(np.int64))


def test_lap_converged_and_residual_observable():
    """ADVICE r5: the result surfaces ``converged`` (completion fallback
    never fired) and ``residual`` (the duality gap certificate, bounded
    by n·ε_eff when the optimality bound holds)."""
    rng = np.random.default_rng(6)
    n = 16
    cost = rng.random((n, n)).astype(np.float32)
    res = solve_lap(cost, epsilon=1e-6)
    assert bool(res.converged)
    # certificate: gap within the stated bound (+ fp slack on the sums)
    assert float(res.residual) <= n * 1e-5 + 1e-4
    assert float(res.residual) >= -1e-4
    # batched shape
    costs = rng.random((3, n, n)).astype(np.float32)
    resb = solve_lap(costs, epsilon=1e-6)
    assert np.asarray(resb.converged).shape == (3,)
    assert np.asarray(resb.residual).shape == (3,)
    assert bool(np.all(np.asarray(resb.converged)))


def test_lap_integer_upcasts_past_f32_ulp_floor():
    """Integer costs whose spread pushes the f32 ULP floor above the
    requested ε are upcast to f64 under x64 (ADVICE r5) — the documented
    integer-exactness guarantee holds instead of silently voiding."""
    rng = np.random.default_rng(7)
    n = 16
    # spread ~2e6 → f32 floor ≈ 2e6·8·1.2e-7 ≈ 1.9 > ε; exactness needs
    # ε < 1/n, unreachable in f32 at this spread
    cost = (rng.integers(0, 2_000_000, (n, n))).astype(np.int64)
    res = solve_lap(cost, epsilon=1.0 / (2 * n))
    r2c = np.asarray(res.row_assignment)
    assert sorted(r2c.tolist()) == list(range(n))
    assert float(res.objective) == scipy_objective(cost)
    # the f64 duals certify it: gap below the integer resolution
    assert abs(float(res.residual)) < 1.0


def test_lap_batched():
    rng = np.random.default_rng(4)
    b, n = 5, 16
    costs = rng.random((b, n, n)).astype(np.float32)
    res = solve_lap(costs, epsilon=1e-7)
    assert res.row_assignment.shape == (b, n)
    for i in range(b):
        ref = scipy_objective(costs[i].astype(np.float64))
        assert float(res.objective[i]) <= ref + n * 1e-5


def test_lap_class_surface_and_duality():
    rng = np.random.default_rng(5)
    n, b = 20, 3
    costs = rng.random((b, n, n)).astype(np.float32)
    lap = LinearAssignmentProblem(size=n, batchsize=b, epsilon=1e-7)
    lap.solve(costs)
    for i in range(b):
        primal = float(lap.get_primal_objective_value(i))
        dual = float(lap.get_dual_objective_value(i))
        # weak duality (dual <= primal) and ε-complementary slackness
        assert dual <= primal + 1e-4
        assert primal - dual <= n * 1e-4
        # feasibility of duals: u_i + v_j <= c_ij (+ tolerance)
        u = np.array(lap.get_row_dual_vector(i))
        v = np.array(lap.get_col_dual_vector(i))
        assert np.all(u[:, None] + v[None, :] <= costs[i] + 1e-4)


def test_lap_diag_structure():
    # cost with an obvious optimal diagonal
    n = 12
    cost = np.full((n, n), 10.0, np.float32)
    np.fill_diagonal(cost, 0.0)
    res = solve_lap(cost, epsilon=1e-6)
    assert np.array_equal(np.array(res.row_assignment), np.arange(n))
    assert float(res.objective) == 0.0


@pytest.mark.parametrize("n,seed", [(12, 5), (25, 6), (40, 7)])
def test_lap_vs_scipy_oracle(n, seed):
    """Optimality vs scipy.optimize.linear_sum_assignment across sizes
    (the reference validates LAP against brute-force/known-optimal costs,
    test/linear_assignment.cu)."""
    from scipy.optimize import linear_sum_assignment

    from raft_tpu.solver import solve_lap

    rng = np.random.default_rng(seed)
    cost = rng.uniform(0, 100, (n, n)).astype(np.float32)
    res = solve_lap(cost)
    rows = np.asarray(res.row_assignment)
    ri, ci = linear_sum_assignment(cost)
    opt = cost[ri, ci].sum()
    got = cost[np.arange(n), rows].sum()
    assert sorted(rows.tolist()) == list(range(n))  # a permutation
    assert got <= opt + 1e-2 * n  # epsilon-optimal within the eps bound


def test_lap_batched_vs_scipy():
    from scipy.optimize import linear_sum_assignment

    from raft_tpu.solver import solve_lap

    rng = np.random.default_rng(8)
    costs = rng.uniform(0, 50, (4, 16, 16)).astype(np.float32)
    res = solve_lap(costs)
    rows = np.asarray(res.row_assignment)
    for b in range(4):
        ri, ci = linear_sum_assignment(costs[b])
        opt = costs[b][ri, ci].sum()
        got = costs[b][np.arange(16), rows[b]].sum()
        assert got <= opt + 1e-2 * 16


def test_lap_adversarial_near_ties():
    """Costs with many near-ties (the auction's hard case: tiny bid
    increments) must still produce a valid epsilon-optimal permutation."""
    from scipy.optimize import linear_sum_assignment

    from raft_tpu.solver import solve_lap

    rng = np.random.default_rng(9)
    n = 20
    base = rng.uniform(0, 1, (n, 1)).astype(np.float32)
    cost = (base + rng.uniform(0, 1e-3, (n, n))).astype(np.float32)
    res = solve_lap(cost, epsilon=1e-7)
    rows = np.asarray(res.row_assignment)
    assert sorted(rows.tolist()) == list(range(n))
    ri, ci = linear_sum_assignment(cost)
    assert cost[np.arange(n), rows].sum() <= cost[ri, ci].sum() + 1e-3


# ---- degenerate / duplicate-cost grids (r5: the auction's tie and
# degeneracy cases vs the scipy oracle — reference test/linear_assignment.cu
# validates against known-optimal structured costs) ----


def _assert_eps_optimal(cost, res, slack):
    n = cost.shape[0]
    rows = np.asarray(res.row_assignment)
    assert sorted(rows.tolist()) == list(range(n))
    ri, ci = linear_sum_assignment(cost.astype(np.float64))
    assert cost[np.arange(n), rows].sum() <= cost[ri, ci].sum() + slack


def test_lap_all_equal_costs():
    """Fully degenerate: every permutation is optimal; the auction must
    still terminate with a valid permutation at the exact objective."""
    n = 16
    cost = np.full((n, n), 7.5, np.float32)
    res = solve_lap(cost, epsilon=1e-6)
    rows = np.asarray(res.row_assignment)
    assert sorted(rows.tolist()) == list(range(n))
    np.testing.assert_allclose(float(res.objective), 7.5 * n, rtol=1e-6)


def test_lap_duplicate_rows_and_columns():
    """Duplicated rows/columns create continuum ties — any optimum is
    acceptable but the objective must match scipy's."""
    rng = np.random.default_rng(10)
    n = 18
    cost = rng.uniform(0, 10, (n, n)).astype(np.float32)
    cost[7] = cost[3]          # duplicate rows
    cost[:, 11] = cost[:, 2]   # duplicate columns
    res = solve_lap(cost, epsilon=1e-7)
    _assert_eps_optimal(cost, res, 1e-3)


def test_lap_rank_one_cost():
    """cost = u·vᵀ is totally degenerate after dual reduction (u_i + v_j
    shifts make all entries equal) — a classic auction stress case."""
    rng = np.random.default_rng(11)
    n = 14
    u = rng.uniform(1, 2, n).astype(np.float32)
    v = rng.uniform(1, 2, n).astype(np.float32)
    cost = np.outer(u, v).astype(np.float32)
    res = solve_lap(cost, epsilon=1e-7)
    _assert_eps_optimal(cost, res, 1e-3)


def test_lap_negative_costs():
    rng = np.random.default_rng(12)
    n = 20
    cost = rng.uniform(-50, 50, (n, n)).astype(np.float32)
    res = solve_lap(cost, epsilon=1e-6)
    _assert_eps_optimal(cost, res, 1e-2 * n)


def test_lap_extreme_dynamic_range():
    """Entries spanning 1e-3..1e6: epsilon scaling must not lose the small
    entries' ordering entirely."""
    rng = np.random.default_rng(13)
    n = 12
    cost = (rng.uniform(0, 1e-3, (n, n))
            + np.where(rng.random((n, n)) < 0.3, 1e6, 0.0)).astype(np.float32)
    # keep at least one cheap entry per row/col: zero diagonal
    np.fill_diagonal(cost, 0.0)
    res = solve_lap(cost, epsilon=1e-4)
    rows = np.asarray(res.row_assignment)
    assert sorted(rows.tolist()) == list(range(n))
    # optimal assignment avoids every 1e6 entry (diagonal is free)
    assert cost[np.arange(n), rows].sum() < 1.0


@pytest.mark.parametrize("n", [1, 2, 3])
def test_lap_minimal_sizes(n):
    rng = np.random.default_rng(14 + n)
    cost = rng.uniform(0, 1, (n, n)).astype(np.float32)
    res = solve_lap(cost, epsilon=1e-8)
    _assert_eps_optimal(cost, res, 1e-4)


def test_lap_permutation_cost_exact():
    """0/1 cost with a unique zero per row/col: the planted permutation is
    the unique optimum and must be recovered EXACTLY."""
    rng = np.random.default_rng(17)
    n = 30
    perm = rng.permutation(n)
    cost = np.ones((n, n), np.float32)
    cost[np.arange(n), perm] = 0.0
    res = solve_lap(cost, epsilon=1.0 / (2 * n))
    np.testing.assert_array_equal(np.asarray(res.row_assignment), perm)
    assert float(res.objective) == 0.0


def test_lap_toeplitz_chain_reassignment():
    """cost[i,j] = |i-j| forces long reassignment chains in the auction
    (each row's best item is contested by its neighbours)."""
    n = 24
    i = np.arange(n)
    cost = np.abs(i[:, None] - i[None, :]).astype(np.float32)
    res = solve_lap(cost, epsilon=1.0 / (2 * n))
    # identity is the unique integer optimum at objective 0
    np.testing.assert_array_equal(np.asarray(res.row_assignment), i)
    assert float(res.objective) == 0.0


def test_lap_batched_mixed_degenerate():
    """A batch mixing degenerate and generic matrices: per-slice optimality
    must hold independently (the vmapped phases share iteration counts)."""
    rng = np.random.default_rng(18)
    n = 16
    costs = np.stack([
        np.full((n, n), 1.0, np.float32),                      # all ties
        rng.uniform(0, 1, (n, n)).astype(np.float32),          # generic
        np.outer(np.ones(n), rng.uniform(0, 1, n)).astype(np.float32),
    ])
    res = solve_lap(costs, epsilon=1e-7)
    for b in range(3):
        rows = np.asarray(res.row_assignment[b])
        assert sorted(rows.tolist()) == list(range(n))
        ri, ci = linear_sum_assignment(costs[b].astype(np.float64))
        assert (costs[b][np.arange(n), rows].sum()
                <= costs[b][ri, ci].sum() + 1e-3)


def test_lap_dual_feasibility_on_degenerate():
    """ε-complementary slackness holds even when ties are everywhere."""
    n = 10
    cost = np.full((n, n), 3.0, np.float32)
    lap = LinearAssignmentProblem(size=n, batchsize=1, epsilon=1e-7)
    lap.solve(cost[None])
    u = np.array(lap.get_row_dual_vector(0))
    v = np.array(lap.get_col_dual_vector(0))
    assert np.all(u[:, None] + v[None, :] <= cost + 1e-4)
    assert float(lap.get_dual_objective_value(0)) <= \
        float(lap.get_primal_objective_value(0)) + 1e-4
