"""LAP auction solver vs scipy.optimize.linear_sum_assignment oracle."""

import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

from raft_tpu.solver import LinearAssignmentProblem, solve_lap


def scipy_objective(cost):
    r, c = linear_sum_assignment(cost)
    return cost[r, c].sum()


@pytest.mark.parametrize("n,seed", [(8, 0), (32, 1), (64, 2)])
def test_lap_float_optimal(n, seed):
    rng = np.random.default_rng(seed)
    cost = rng.random((n, n)).astype(np.float32)
    res = solve_lap(cost, epsilon=1e-7)
    r2c = np.array(res.row_assignment)
    # valid permutation
    assert sorted(r2c.tolist()) == list(range(n))
    # col_assignment is the inverse permutation
    c2r = np.array(res.col_assignment)
    assert np.array_equal(c2r[r2c], np.arange(n))
    # within n·eps of scipy's optimum
    ref = scipy_objective(cost.astype(np.float64))
    assert float(res.objective) <= ref + n * 1e-5
    np.testing.assert_allclose(float(res.objective),
                               cost[np.arange(n), r2c].sum(), rtol=1e-5)


def test_lap_integer_exact():
    rng = np.random.default_rng(3)
    n = 24
    cost = rng.integers(0, 100, (n, n)).astype(np.float32)
    res = solve_lap(cost, epsilon=1.0 / (2 * n))
    r2c = np.array(res.row_assignment)
    assert sorted(r2c.tolist()) == list(range(n))
    # integer costs + eps < 1/n → provably exact optimum
    assert float(res.objective) == scipy_objective(cost.astype(np.int64))


def test_lap_batched():
    rng = np.random.default_rng(4)
    b, n = 5, 16
    costs = rng.random((b, n, n)).astype(np.float32)
    res = solve_lap(costs, epsilon=1e-7)
    assert res.row_assignment.shape == (b, n)
    for i in range(b):
        ref = scipy_objective(costs[i].astype(np.float64))
        assert float(res.objective[i]) <= ref + n * 1e-5


def test_lap_class_surface_and_duality():
    rng = np.random.default_rng(5)
    n, b = 20, 3
    costs = rng.random((b, n, n)).astype(np.float32)
    lap = LinearAssignmentProblem(size=n, batchsize=b, epsilon=1e-7)
    lap.solve(costs)
    for i in range(b):
        primal = float(lap.get_primal_objective_value(i))
        dual = float(lap.get_dual_objective_value(i))
        # weak duality (dual <= primal) and ε-complementary slackness
        assert dual <= primal + 1e-4
        assert primal - dual <= n * 1e-4
        # feasibility of duals: u_i + v_j <= c_ij (+ tolerance)
        u = np.array(lap.get_row_dual_vector(i))
        v = np.array(lap.get_col_dual_vector(i))
        assert np.all(u[:, None] + v[None, :] <= costs[i] + 1e-4)


def test_lap_diag_structure():
    # cost with an obvious optimal diagonal
    n = 12
    cost = np.full((n, n), 10.0, np.float32)
    np.fill_diagonal(cost, 0.0)
    res = solve_lap(cost, epsilon=1e-6)
    assert np.array_equal(np.array(res.row_assignment), np.arange(n))
    assert float(res.objective) == 0.0
