"""LAP auction solver vs scipy.optimize.linear_sum_assignment oracle."""

import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

from raft_tpu.solver import LinearAssignmentProblem, solve_lap


def scipy_objective(cost):
    r, c = linear_sum_assignment(cost)
    return cost[r, c].sum()


@pytest.mark.parametrize("n,seed", [(8, 0), (32, 1), (64, 2)])
def test_lap_float_optimal(n, seed):
    rng = np.random.default_rng(seed)
    cost = rng.random((n, n)).astype(np.float32)
    res = solve_lap(cost, epsilon=1e-7)
    r2c = np.array(res.row_assignment)
    # valid permutation
    assert sorted(r2c.tolist()) == list(range(n))
    # col_assignment is the inverse permutation
    c2r = np.array(res.col_assignment)
    assert np.array_equal(c2r[r2c], np.arange(n))
    # within n·eps of scipy's optimum
    ref = scipy_objective(cost.astype(np.float64))
    assert float(res.objective) <= ref + n * 1e-5
    np.testing.assert_allclose(float(res.objective),
                               cost[np.arange(n), r2c].sum(), rtol=1e-5)


def test_lap_integer_exact():
    rng = np.random.default_rng(3)
    n = 24
    cost = rng.integers(0, 100, (n, n)).astype(np.float32)
    res = solve_lap(cost, epsilon=1.0 / (2 * n))
    r2c = np.array(res.row_assignment)
    assert sorted(r2c.tolist()) == list(range(n))
    # integer costs + eps < 1/n → provably exact optimum
    assert float(res.objective) == scipy_objective(cost.astype(np.int64))


def test_lap_batched():
    rng = np.random.default_rng(4)
    b, n = 5, 16
    costs = rng.random((b, n, n)).astype(np.float32)
    res = solve_lap(costs, epsilon=1e-7)
    assert res.row_assignment.shape == (b, n)
    for i in range(b):
        ref = scipy_objective(costs[i].astype(np.float64))
        assert float(res.objective[i]) <= ref + n * 1e-5


def test_lap_class_surface_and_duality():
    rng = np.random.default_rng(5)
    n, b = 20, 3
    costs = rng.random((b, n, n)).astype(np.float32)
    lap = LinearAssignmentProblem(size=n, batchsize=b, epsilon=1e-7)
    lap.solve(costs)
    for i in range(b):
        primal = float(lap.get_primal_objective_value(i))
        dual = float(lap.get_dual_objective_value(i))
        # weak duality (dual <= primal) and ε-complementary slackness
        assert dual <= primal + 1e-4
        assert primal - dual <= n * 1e-4
        # feasibility of duals: u_i + v_j <= c_ij (+ tolerance)
        u = np.array(lap.get_row_dual_vector(i))
        v = np.array(lap.get_col_dual_vector(i))
        assert np.all(u[:, None] + v[None, :] <= costs[i] + 1e-4)


def test_lap_diag_structure():
    # cost with an obvious optimal diagonal
    n = 12
    cost = np.full((n, n), 10.0, np.float32)
    np.fill_diagonal(cost, 0.0)
    res = solve_lap(cost, epsilon=1e-6)
    assert np.array_equal(np.array(res.row_assignment), np.arange(n))
    assert float(res.objective) == 0.0


@pytest.mark.parametrize("n,seed", [(12, 5), (25, 6), (40, 7)])
def test_lap_vs_scipy_oracle(n, seed):
    """Optimality vs scipy.optimize.linear_sum_assignment across sizes
    (the reference validates LAP against brute-force/known-optimal costs,
    test/linear_assignment.cu)."""
    from scipy.optimize import linear_sum_assignment

    from raft_tpu.solver import solve_lap

    rng = np.random.default_rng(seed)
    cost = rng.uniform(0, 100, (n, n)).astype(np.float32)
    res = solve_lap(cost)
    rows = np.asarray(res.row_assignment)
    ri, ci = linear_sum_assignment(cost)
    opt = cost[ri, ci].sum()
    got = cost[np.arange(n), rows].sum()
    assert sorted(rows.tolist()) == list(range(n))  # a permutation
    assert got <= opt + 1e-2 * n  # epsilon-optimal within the eps bound


def test_lap_batched_vs_scipy():
    from scipy.optimize import linear_sum_assignment

    from raft_tpu.solver import solve_lap

    rng = np.random.default_rng(8)
    costs = rng.uniform(0, 50, (4, 16, 16)).astype(np.float32)
    res = solve_lap(costs)
    rows = np.asarray(res.row_assignment)
    for b in range(4):
        ri, ci = linear_sum_assignment(costs[b])
        opt = costs[b][ri, ci].sum()
        got = costs[b][np.arange(16), rows[b]].sum()
        assert got <= opt + 1e-2 * 16


def test_lap_adversarial_near_ties():
    """Costs with many near-ties (the auction's hard case: tiny bid
    increments) must still produce a valid epsilon-optimal permutation."""
    from scipy.optimize import linear_sum_assignment

    from raft_tpu.solver import solve_lap

    rng = np.random.default_rng(9)
    n = 20
    base = rng.uniform(0, 1, (n, 1)).astype(np.float32)
    cost = (base + rng.uniform(0, 1e-3, (n, n))).astype(np.float32)
    res = solve_lap(cost, epsilon=1e-7)
    rows = np.asarray(res.row_assignment)
    assert sorted(rows.tolist()) == list(range(n))
    ri, ci = linear_sum_assignment(cost)
    assert cost[np.arange(n), rows].sum() <= cost[ri, ci].sum() + 1e-3
