"""Sparse containers/convert/op/linalg vs scipy.sparse oracles
(reference test strategy SURVEY.md §4: naive-oracle comparisons)."""

import numpy as np
import pytest
import scipy.sparse as sp


from raft_tpu.sparse import (
    COO,
    CSR,
    adj_to_csr,
    coo_remove_zeros,
    coo_sort,
    coo_sum_duplicates,
    coo_to_dense,
    csr_add,
    csr_degree,
    csr_row_slice,
    csr_to_coo,
    csr_to_dense,
    csr_transpose,
    dense_to_csr,
    laplacian,
    row_normalize,
    spmm,
    spmv,
    symmetrize,
)


def random_csr(m, n, density=0.3, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    s = sp.random(m, n, density=density, random_state=rng, format="csr",
                  dtype=dtype)
    return s


def to_raft(s: sp.csr_matrix, extra_capacity=0) -> CSR:
    pad = extra_capacity
    indices = np.concatenate([s.indices, np.zeros(pad, np.int32)])
    data = np.concatenate([s.data, np.zeros(pad, s.data.dtype)])
    return CSR(s.indptr, indices, data, s.shape)


@pytest.mark.parametrize("m,n", [(7, 5), (16, 16), (33, 9)])
@pytest.mark.parametrize("pad", [0, 13])
def test_roundtrip_dense_csr(m, n, pad):
    s = random_csr(m, n, seed=m * n)
    csr = to_raft(s, pad)
    np.testing.assert_allclose(csr_to_dense(csr), s.toarray(), rtol=1e-6)
    # dense → csr → dense
    back = dense_to_csr(s.toarray())
    np.testing.assert_allclose(csr_to_dense(back), s.toarray(), rtol=1e-6)
    assert int(back.nnz) == s.nnz


def test_coo_roundtrip_and_sort():
    s = random_csr(10, 8, seed=3)
    coo = csr_to_coo(to_raft(s, 5))
    np.testing.assert_allclose(coo_to_dense(coo), s.toarray(), rtol=1e-6)
    # scramble then sort
    rng = np.random.default_rng(0)
    perm = rng.permutation(coo.capacity)
    scrambled = COO(np.array(coo.rows)[perm], np.array(coo.cols)[perm],
                    np.array(coo.vals)[perm], coo.shape, nnz=coo.nnz)
    srt = coo_sort(scrambled)
    np.testing.assert_allclose(coo_to_dense(srt), s.toarray(), rtol=1e-6)
    rows = np.array(srt.rows)[: s.nnz]
    assert (np.diff(rows) >= 0).all()


def test_coo_remove_zeros():
    rows = np.array([0, 0, 1, 2, 3], np.int32)
    cols = np.array([1, 2, 0, 2, 3], np.int32)
    vals = np.array([1.0, 0.0, 2.0, 0.0, 3.0], np.float32)
    coo = COO(rows, cols, vals, (4, 4))
    out = coo_remove_zeros(coo)
    assert int(out.nnz) == 3
    dense = np.zeros((4, 4), np.float32)
    dense[0, 1], dense[1, 0], dense[3, 3] = 1, 2, 3
    np.testing.assert_allclose(coo_to_dense(out), dense)


def test_coo_sum_duplicates():
    rows = np.array([2, 0, 0, 2, 1], np.int32)
    cols = np.array([1, 3, 3, 1, 0], np.int32)
    vals = np.array([1.0, 2.0, 5.0, 4.0, 3.0], np.float32)
    coo = COO(rows, cols, vals, (3, 4))
    out = coo_sum_duplicates(coo)
    assert int(out.nnz) == 3
    dense = np.zeros((3, 4), np.float32)
    dense[2, 1], dense[0, 3], dense[1, 0] = 5, 7, 3
    np.testing.assert_allclose(coo_to_dense(out), dense)


@pytest.mark.parametrize("m,n,k", [(9, 7, 4), (16, 16, 16)])
def test_spmv_spmm(m, n, k):
    s = random_csr(m, n, seed=5)
    csr = to_raft(s, 7)
    rng = np.random.default_rng(1)
    x = rng.random(n).astype(np.float32)
    b = rng.random((n, k)).astype(np.float32)
    np.testing.assert_allclose(spmv(csr, x), s @ x, rtol=2e-5)
    np.testing.assert_allclose(spmm(csr, b), s @ b, rtol=2e-5)


def test_degree_and_row_normalize():
    s = random_csr(12, 6, seed=7)
    csr = to_raft(s, 3)
    deg = np.diff(s.indptr)
    np.testing.assert_array_equal(csr_degree(csr), deg)
    rn = row_normalize(csr, "l1")
    dense = csr_to_dense(rn)
    expected = s.toarray()
    sums = np.abs(expected).sum(1, keepdims=True)
    sums[sums == 0] = 1
    np.testing.assert_allclose(dense, expected / sums, rtol=1e-5)


@pytest.mark.slow  # CSR transpose+add vs scipy oracle (tier-1 budget)
def test_transpose_add():
    a = random_csr(8, 11, seed=11)
    b = random_csr(8, 11, seed=13)
    np.testing.assert_allclose(
        csr_to_dense(csr_transpose(to_raft(a, 4))), a.toarray().T, rtol=1e-6)
    out = csr_add(to_raft(a), to_raft(b))
    np.testing.assert_allclose(csr_to_dense(out), (a + b).toarray(), rtol=1e-5)


def test_symmetrize():
    a = random_csr(9, 9, seed=17)
    out = symmetrize(to_raft(a, 6))
    np.testing.assert_allclose(csr_to_dense(out), (a + a.T).toarray(),
                               rtol=1e-5)


@pytest.mark.parametrize("normalized", [False, True])
def test_laplacian(normalized):
    rng = np.random.default_rng(23)
    n = 10
    dense = (rng.random((n, n)) < 0.3).astype(np.float32)
    dense = np.maximum(dense, dense.T)
    np.fill_diagonal(dense, 0)
    s = sp.csr_matrix(dense)
    lap = laplacian(to_raft(s, 8), normalized=normalized)
    deg = dense.sum(1)
    if normalized:
        with np.errstate(divide="ignore"):
            isq = np.where(deg > 0, 1 / np.sqrt(deg), 0)
        expected = np.where(deg > 0, 1.0, 0.0) * np.eye(n) - isq[:, None] * dense * isq[None, :]
    else:
        expected = np.diag(deg) - dense
    np.testing.assert_allclose(csr_to_dense(lap), expected, atol=1e-5)


def test_csr_row_slice():
    s = random_csr(12, 7, seed=29)
    out = csr_row_slice(to_raft(s, 9), 3, 9)
    np.testing.assert_allclose(csr_to_dense(out), s.toarray()[3:9], rtol=1e-6)


def test_adj_to_csr():
    rng = np.random.default_rng(31)
    adj = rng.random((6, 9)) < 0.4
    out = adj_to_csr(adj)
    np.testing.assert_allclose(csr_to_dense(out), adj.astype(np.float32))


def test_ell_hybrid_matches_spmv():
    import scipy.sparse as sp
    from raft_tpu.sparse import csr_to_ell, ell_spmv, spmv

    rng = np.random.default_rng(5)
    # skewed rows: a few dense rows force the COO overflow path
    g = sp.random(300, 300, density=0.02, format="lil", dtype=np.float32,
                  random_state=3)
    g[7, :150] = rng.random(150)
    g[42, :80] = rng.random(80)
    g = g.tocsr()
    a = CSR(g.indptr, g.indices, g.data, g.shape)
    ell = csr_to_ell(a)
    assert ell.ov_rows.shape[0] > 0  # overflow exercised
    x = rng.random(300).astype(np.float32)
    np.testing.assert_allclose(np.asarray(ell_spmv(ell, x)),
                               np.asarray(spmv(a, x)), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ell_spmv(ell, x)), g @ x,
                               rtol=1e-4, atol=1e-4)


class TestScipyOracleGrids:
    """Random-matrix grids against scipy.sparse (reference sparse tests
    run fixed cases; a seeded grid covers shapes, densities, and dtypes)."""

    @pytest.mark.parametrize("m,n,density,seed", [
        (10, 10, 0.1, 0), (40, 25, 0.3, 1), (64, 64, 0.02, 2),
        (7, 33, 0.5, 3),
    ])
    def test_add_transpose_grid(self, m, n, density, seed):
        a = random_csr(m, n, density, seed)
        b = random_csr(m, n, density, seed + 100)
        got = csr_to_dense(csr_add(to_raft(a), to_raft(b)))
        np.testing.assert_allclose(np.asarray(got), (a + b).toarray(),
                                   atol=1e-6)
        got_t = csr_to_dense(csr_transpose(to_raft(a)))
        np.testing.assert_allclose(np.asarray(got_t), a.T.toarray(),
                                   atol=1e-6)

    @pytest.mark.parametrize("combine", ["sum", "max", "min"])
    def test_symmetrize_combine_modes(self, combine):
        a = random_csr(12, 12, 0.25, 4)
        got = csr_to_dense(symmetrize(to_raft(a), combine=combine))
        d = a.toarray()
        if combine == "sum":
            want = d + d.T
        elif combine == "max":
            want = np.maximum(d, d.T)
        else:
            # min over the nonzero union: zeros are "absent", not value 0
            # (reference symmetrize operates on the edge set)
            both = (d != 0) & (d.T != 0)
            want = np.where(both, np.minimum(d, d.T), d + d.T)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)

    @pytest.mark.parametrize("norm", ["l1", "max"])
    def test_row_normalize_modes(self, norm):
        """The reference surface is l1/max only
        (sparse/linalg/norm.cuh csr_row_normalize_l1 / _max)."""
        a = random_csr(20, 15, 0.3, 5)  # nonneg data: max == abs-max
        got = csr_to_dense(row_normalize(to_raft(a), norm=norm))
        d = a.toarray()
        scale = {"l1": np.abs(d).sum(1), "max": d.max(axis=1)}[norm]
        want = np.where(scale[:, None] > 0,
                        d / np.maximum(scale, 1e-30)[:, None], 0.0)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)

    def test_row_normalize_unknown_mode_rejected(self):
        a = random_csr(4, 4, 0.5, 6)
        with pytest.raises(ValueError):
            row_normalize(to_raft(a), norm="l2")

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_spmv_spmm_dtype_grid(self, dtype):
        a = random_csr(30, 22, 0.2, 6, dtype=dtype)
        x = np.random.default_rng(7).random(22).astype(dtype)
        b = np.random.default_rng(8).random((22, 5)).astype(dtype)
        tol = 1e-5 if dtype == np.float32 else 1e-12
        np.testing.assert_allclose(np.asarray(spmv(to_raft(a), x)), a @ x,
                                   atol=tol)
        np.testing.assert_allclose(np.asarray(spmm(to_raft(a), b)), a @ b,
                                   atol=tol)

    @pytest.mark.slow  # quantile-split grid vs scipy oracle (budget)
    def test_ell_quantile_split(self):
        """csr_to_ell puts at most the q-quantile row degree in the ELL
        part; the COO tail holds the rest; spmv equivalence holds at
        every quantile."""
        from raft_tpu.sparse import csr_to_ell, ell_spmv

        rng = np.random.default_rng(9)
        # skewed degrees: one hub row
        d = (rng.random((40, 40)) < 0.05).astype(np.float32)
        d[3, :] = 1.0
        s = sp.csr_matrix(d)
        x = rng.random(40).astype(np.float32)
        want = s @ x
        for q in (0.5, 0.9, 1.0):
            ell = csr_to_ell(to_raft(s), quantile=q)
            np.testing.assert_allclose(np.asarray(ell_spmv(ell, x)), want,
                                       atol=1e-5, err_msg=f"q={q}")
