"""Sparse distances / sparse kNN / kNN-graph MST / single-linkage KNN mode."""

import numpy as np
import pytest
import scipy.sparse as sp
from scipy.spatial.distance import cdist

from raft_tpu.distance import DistanceType
from raft_tpu.sparse import CSR
from raft_tpu.sparse.distance import SUPPORTED_SPARSE_DISTANCES, pairwise_distance
from raft_tpu.sparse.neighbors import (
    brute_force_knn,
    build_k,
    connect_components,
    knn_graph,
    mst_from_knn_graph,
)

SCIPY_NAMES = {
    DistanceType.L2Expanded: "sqeuclidean",
    DistanceType.L2SqrtExpanded: "euclidean",
    DistanceType.CosineExpanded: "cosine",
    DistanceType.L1: "cityblock",
    DistanceType.Linf: "chebyshev",
    DistanceType.Canberra: "canberra",
}


def to_raft(s: sp.csr_matrix, pad=0) -> CSR:
    indices = np.concatenate([s.indices, np.zeros(pad, np.int32)])
    data = np.concatenate([s.data, np.zeros(pad, s.data.dtype)])
    return CSR(s.indptr, indices, data, s.shape)


def random_csr(m, n, density=0.4, seed=0):
    rng = np.random.default_rng(seed)
    return sp.random(m, n, density=density, random_state=rng, format="csr",
                     dtype=np.float32)


@pytest.mark.parametrize("metric", list(SCIPY_NAMES))
def test_sparse_pairwise_vs_scipy(metric):
    a = random_csr(33, 20, seed=1)
    b = random_csr(27, 20, seed=2)
    d = np.asarray(pairwise_distance(to_raft(a, 5), to_raft(b, 3), metric))
    ref = cdist(a.toarray(), b.toarray(), SCIPY_NAMES[metric])
    np.testing.assert_allclose(d, ref, rtol=1e-3, atol=1e-5)


ALL_COMPRESSED = [m for m in SUPPORTED_SPARSE_DISTANCES]


@pytest.mark.parametrize("metric", ALL_COMPRESSED,
                         ids=[m.name for m in ALL_COMPRESSED])
def test_compressed_engine_matches_densify(metric):
    """The feature-compressed (high-dim) engine must agree with the
    block-densify engine on every metric — batched so the compressed path
    exercises outside-u corrections across block boundaries."""
    from raft_tpu.distance import DistanceType as DT
    from raft_tpu.sparse.distance import _COMPRESSED_ONLY

    density = 0.15
    a = random_csr(37, 64, density=density, seed=7)
    b = random_csr(29, 64, density=density, seed=8)
    if metric in (DT.HellingerExpanded, DT.JensenShannon, DT.KLDivergence):
        a.data, b.data = np.abs(a.data) + 0.1, np.abs(b.data) + 0.1
    got = np.asarray(pairwise_distance(
        to_raft(a, 4), to_raft(b, 2), metric, engine="compressed",
        batch_size_x=16, batch_size_y=11))
    if metric in _COMPRESSED_ONLY:
        # no densify reference — check against a direct numpy formula
        ad, bd = a.toarray(), b.toarray()
        dot = ad @ bd.T
        union = ad.sum(1)[:, None] + bd.sum(1)[None, :]
        if metric == DT.JaccardExpanded:
            denom = union - dot
            sim = np.where(denom != 0, dot / np.where(denom != 0, denom, 1), 0)
        else:
            sim = np.where(union != 0, 2 * dot / np.where(union != 0, union, 1), 0)
        ref = np.where(union == 0, 0.0, 1.0 - sim)
    else:
        ref = np.asarray(pairwise_distance(to_raft(a), to_raft(b), metric,
                                           engine="densify"))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-5)


@pytest.mark.parametrize("metric", [DistanceType.L2SqrtExpanded,
                                    DistanceType.L1,
                                    DistanceType.CosineExpanded,
                                    DistanceType.Linf])
def test_highdim_sparse_bounded_memory(metric):
    """dim = 50_000, ~20 nnz/row: densifying would need blocks × 50k; the
    compressed engine's tiles are O(block_nnz) regardless of dim
    (reference coo_spmv.cuh hash-strategy territory)."""
    dim, nnz_row, m, n = 50_000, 20, 150, 120
    rng = np.random.default_rng(0)

    def make(rows, seed):
        r = np.random.default_rng(seed)
        cols = np.concatenate([np.sort(r.choice(dim, nnz_row, replace=False))
                               for _ in range(rows)]).astype(np.int32)
        vals = r.random(rows * nnz_row).astype(np.float32) + 0.1
        indptr = np.arange(rows + 1, dtype=np.int32) * nnz_row
        s = sp.csr_matrix((vals, cols, indptr), shape=(rows, dim))
        return s

    a, b = make(m, 1), make(n, 2)
    got = np.asarray(pairwise_distance(to_raft(a), to_raft(b), metric,
                                       batch_size_x=64, batch_size_y=64))
    name = SCIPY_NAMES[metric]
    ref = cdist(a.toarray(), b.toarray(), name)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-5)


def test_auto_engine_picks_compressed_for_highdim():

    a = random_csr(10, 16, seed=11)
    a.data[:] = 1.0  # the jaccard formula presumes boolean-valued rows
    # jaccard has no densify path at any dim; must not raise
    d = np.asarray(pairwise_distance(to_raft(a), to_raft(a),
                                     DistanceType.JaccardExpanded))
    assert np.allclose(np.diag(d), 0.0, atol=1e-6)
    assert (d >= -1e-6).all()


# tiling oracle; the ragged-batch kNN param below exercises the same
# tiled path (tier-1 budget, PR 4)
@pytest.mark.slow
def test_sparse_pairwise_batched_matches_unbatched():
    a = random_csr(50, 16, seed=3)
    b = random_csr(40, 16, seed=4)
    full = np.asarray(pairwise_distance(to_raft(a), to_raft(b),
                                        DistanceType.L2SqrtExpanded))
    tiled = np.asarray(pairwise_distance(to_raft(a), to_raft(b),
                                         DistanceType.L2SqrtExpanded,
                                         batch_size_x=16, batch_size_y=17))
    np.testing.assert_allclose(tiled, full, rtol=1e-5)


@pytest.mark.parametrize("batch", [
    # single-tile shape; the ragged (13, 11) param covers the tiled
    # path (budget, PR 4)
    pytest.param((16384, 4096), marks=pytest.mark.slow),
    (13, 11),
])
def test_sparse_brute_force_knn(batch):
    bi, bq = batch
    index = random_csr(60, 12, seed=5)
    query = random_csr(25, 12, seed=6)
    d, i = brute_force_knn(to_raft(index), to_raft(query), k=5,
                           batch_size_index=bi, batch_size_query=bq)
    ref = cdist(query.toarray(), index.toarray(), "sqeuclidean")
    ref_i = np.argsort(ref, axis=1, kind="stable")[:, :5]
    ref_d = np.take_along_axis(ref, ref_i, axis=1)
    np.testing.assert_allclose(np.asarray(d), ref_d, rtol=1e-3, atol=1e-5)
    # indices may differ on ties; distances must match


def test_build_k():
    assert build_k(1024, 5) == 15
    assert build_k(4, 1) == 3
    assert build_k(2, 50) == 2


def test_knn_graph():
    rng = np.random.default_rng(8)
    x = rng.random((30, 4)).astype(np.float32)
    g = knn_graph(x, DistanceType.L2SqrtExpanded, k=3)
    rows = np.asarray(g.rows)
    cols = np.asarray(g.cols)
    vals = np.asarray(g.vals)
    assert rows.shape[0] == 30 * 3
    ref = cdist(x, x)
    np.fill_diagonal(ref, np.inf)
    for i in range(30):
        mine = set(cols[rows == i])
        theirs = set(np.argsort(ref[i])[:3])
        assert mine == theirs
        np.testing.assert_allclose(np.sort(vals[rows == i]),
                                   np.sort(ref[i, list(theirs)]), rtol=1e-4)


def test_connect_components_reduces():
    rng = np.random.default_rng(9)
    x = np.concatenate([rng.random((10, 3)), rng.random((10, 3)) + 10]).astype(np.float32)
    colors = np.array([0] * 10 + [1] * 10, np.int32)
    edges = connect_components(x, colors)
    rows = np.asarray(edges.rows)
    cols = np.asarray(edges.cols)
    live = rows < 20
    assert live.sum() >= 2  # at least one edge + its reverse
    crosses = colors[rows[live]] != colors[cols[live]]
    assert crosses.all()


def test_mst_from_knn_graph_connects():
    rng = np.random.default_rng(10)
    # three far-apart blobs — kNN graph (small k) is disconnected, fix-up
    # must stitch it into a single tree
    x = np.concatenate([rng.random((15, 2)),
                        rng.random((15, 2)) + 50,
                        rng.random((15, 2)) + 100]).astype(np.float32)
    src, dst, w = mst_from_knn_graph(x, c=2)
    n = 45
    src, dst, w = np.asarray(src)[: n - 1], np.asarray(dst)[: n - 1], np.asarray(w)[: n - 1]
    # forms a spanning tree
    parent = list(range(n))

    def find(a):
        while parent[a] != a:
            parent[a] = parent[parent[a]]
            a = parent[a]
        return a

    for s, t in zip(src, dst):
        rs, rt = find(int(s)), find(int(t))
        assert rs != rt
        parent[rs] = rt
    assert len({find(i) for i in range(n)}) == 1
    assert (np.diff(w) >= 0).all()


def test_single_linkage_knn_graph_mode():
    from raft_tpu.cluster import LinkageDistance, single_linkage

    rng = np.random.default_rng(11)
    x = np.concatenate([rng.random((20, 2)),
                        rng.random((20, 2)) + 10]).astype(np.float32)
    out = single_linkage(x, linkage=LinkageDistance.KNN_GRAPH, n_clusters=2)
    labels = np.asarray(out.labels)
    assert len(np.unique(labels[:20])) == 1
    assert len(np.unique(labels[20:])) == 1
    assert labels[0] != labels[20]


def test_sparse_distance_empty_rows_conventions():
    """Rows with no stored entries (explicitly zero rows) follow the
    dense-engine conventions: L2/Jaccard self-distance 0, cosine distance
    of a zero vector defined as 1 (no NaNs anywhere)."""
    import scipy.sparse as sp

    from raft_tpu.distance import DistanceType
    from raft_tpu.sparse import CSR
    from raft_tpu.sparse.distance import pairwise_distance as spd

    g = sp.random(6, 10, density=0.3, format="csr", dtype=np.float32,
                  random_state=0)
    gl = g.tolil()
    gl[2] = 0
    ge = gl.tocsr()
    ge.eliminate_zeros()
    a = CSR(ge.indptr, ge.indices, ge.data, ge.shape)
    for metric, self_d in ((DistanceType.L2Expanded, 0.0),
                           (DistanceType.JaccardExpanded, 0.0),
                           (DistanceType.CosineExpanded, 1.0)):
        d = np.asarray(spd(a, a, metric))
        assert not np.isnan(d).any(), metric
        assert d[2, 2] == pytest.approx(self_d, abs=1e-6), metric
