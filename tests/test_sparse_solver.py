"""Lanczos / Borůvka MST / weak_cc / fit_embedding vs scipy oracles."""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.csgraph as csgraph

from raft_tpu.sparse import (
    CSR,
    boruvka_mst,
    dense_to_csr,
    fit_embedding,
    lanczos_largest,
    lanczos_smallest,
    laplacian,
    weak_cc,
)
from raft_tpu.sparse.solver.mst import sorted_mst_edges


def to_raft(s: sp.csr_matrix, pad=0) -> CSR:
    indices = np.concatenate([s.indices, np.zeros(pad, np.int32)])
    data = np.concatenate([s.data, np.zeros(pad, s.data.dtype)])
    return CSR(s.indptr, indices, data, s.shape)


def random_sym_graph(n, density=0.2, seed=0, connected=False):
    rng = np.random.default_rng(seed)
    d = rng.random((n, n)).astype(np.float32)
    mask = rng.random((n, n)) < density
    d = d * mask
    d = np.triu(d, 1)
    if connected:
        # ring to guarantee connectivity
        for i in range(n):
            d[min(i, (i + 1) % n), max(i, (i + 1) % n)] = rng.random() + 0.1
    d = d + d.T
    return d


@pytest.mark.parametrize("n,k", [
    (40, 3),
    pytest.param(80, 5, marks=pytest.mark.slow),  # tier-1 budget
])
def test_lanczos_smallest_vs_numpy(n, k):
    d = random_sym_graph(n, 0.3, seed=n, connected=True)
    lap = laplacian(dense_to_csr(d))
    evals, evecs = lanczos_smallest(lap, k, tol=1e-8)
    dense_lap = np.diag(d.sum(1)) - d
    ref = np.linalg.eigvalsh(dense_lap)[:k]
    np.testing.assert_allclose(np.sort(np.array(evals)), ref, atol=1e-3)
    # Residual check ||A v - λ v||
    for i in range(k):
        v = np.array(evecs[:, i])
        r = dense_lap @ v - float(evals[i]) * v
        assert np.linalg.norm(r) < 1e-2


def test_lanczos_largest_vs_numpy():
    n, k = 60, 4
    d = random_sym_graph(n, 0.3, seed=9, connected=True)
    csr = dense_to_csr(d)
    evals, evecs = lanczos_largest(csr, k, tol=1e-8)
    ref = np.linalg.eigvalsh(d)[::-1][:k]
    np.testing.assert_allclose(np.array(evals), ref, atol=1e-3)


@pytest.mark.parametrize("n,seed", [
    pytest.param(30, 0, marks=pytest.mark.slow),  # budget (PR 4)
    pytest.param(64, 1, marks=pytest.mark.slow),  # budget (PR 4)
    (100, 2),
])
def test_boruvka_mst_matches_scipy(n, seed):
    d = random_sym_graph(n, 0.25, seed=seed, connected=True)
    res = boruvka_mst(dense_to_csr(d))
    assert int(res.n_edges) == n - 1
    total = float(np.sum(np.array(res.weight)[: n - 1]))
    ref = csgraph.minimum_spanning_tree(sp.csr_matrix(d)).sum()
    np.testing.assert_allclose(total, ref, rtol=1e-5)
    # single component
    assert len(np.unique(np.array(res.color))) == 1
    # sorted edges ascending
    src, dst, w = sorted_mst_edges(res)
    ws = np.array(w)[: n - 1]
    assert (np.diff(ws) >= 0).all()


def test_boruvka_forest_disconnected():
    # two cliques, no cross edges
    rng = np.random.default_rng(5)
    n = 20
    d = np.zeros((n, n), np.float32)
    for block in (slice(0, 10), slice(10, 20)):
        b = rng.random((10, 10)).astype(np.float32)
        b = np.triu(b, 1)
        d[block, block] = b + b.T
    res = boruvka_mst(dense_to_csr(d))
    assert int(res.n_edges) == n - 2
    colors = np.array(res.color)
    assert len(np.unique(colors)) == 2
    assert len(np.unique(colors[:10])) == 1 and len(np.unique(colors[10:])) == 1
    ref = csgraph.minimum_spanning_tree(sp.csr_matrix(d)).sum()
    total = float(np.sum(np.array(res.weight)[: n - 2]))
    np.testing.assert_allclose(total, ref, rtol=1e-5)


def test_boruvka_ties():
    # all weights equal → any spanning tree has the same cost; must not
    # produce cycles or duplicates.
    n = 16
    d = np.ones((n, n), np.float32) - np.eye(n, dtype=np.float32)
    res = boruvka_mst(dense_to_csr(d))
    assert int(res.n_edges) == n - 1
    np.testing.assert_allclose(float(np.sum(np.array(res.weight)[: n - 1])),
                               n - 1)
    # edges must form a tree: union-find check
    parent = list(range(n))

    def find(a):
        while parent[a] != a:
            parent[a] = parent[parent[a]]
            a = parent[a]
        return a

    for s, t in zip(np.array(res.src)[: n - 1], np.array(res.dst)[: n - 1]):
        rs, rt = find(int(s)), find(int(t))
        assert rs != rt, "cycle in MST output"
        parent[rs] = rt


def test_weak_cc_directed():
    # weak connectivity ignores edge direction
    d = np.zeros((3, 3), np.float32)
    d[0, 1] = 1.0
    labels = np.array(weak_cc(dense_to_csr(d)))
    assert labels[0] == labels[1] != labels[2]


def test_coo_degree():
    from raft_tpu.sparse import coo_degree, csr_to_coo

    d = np.zeros((4, 4), np.float32)
    d[0, 1] = d[0, 2] = d[2, 3] = 1.0
    deg = np.array(coo_degree(csr_to_coo(dense_to_csr(d))))
    np.testing.assert_array_equal(deg, [2, 0, 1, 0])


def test_weak_cc():
    d = np.zeros((9, 9), np.float32)
    for a, b in [(0, 1), (1, 2), (3, 4), (5, 6), (6, 7), (7, 8)]:
        d[a, b] = d[b, a] = 1.0
    labels = np.array(weak_cc(dense_to_csr(d)))
    assert len(np.unique(labels)) == 3
    assert labels[0] == labels[1] == labels[2]
    assert labels[3] == labels[4]
    assert labels[5] == labels[6] == labels[7] == labels[8]


def test_fit_embedding_separates_blocks():
    # two dense blocks weakly joined: the Fiedler vector separates them
    rng = np.random.default_rng(7)
    n = 40
    d = np.zeros((n, n), np.float32)
    for block in (slice(0, 20), slice(20, 40)):
        b = (rng.random((20, 20)) < 0.7).astype(np.float32)
        b = np.triu(b, 1)
        d[block, block] = b + b.T
    d[0, 20] = d[20, 0] = 0.01
    emb = np.array(fit_embedding(dense_to_csr(d), 2, tol=1e-8))
    assert emb.shape == (n, 2)
    side = emb[:, 0] > np.median(emb[:, 0])
    # all of block 1 on one side, block 2 on the other
    assert len(np.unique(side[:20])) == 1
    assert len(np.unique(side[20:])) == 1
    assert side[0] != side[20]


@pytest.mark.slow  # dense-spectrum convergence stress (tier-1 budget)
def test_lanczos_clustered_spectrum():
    """Near-degenerate eigenvalue clusters must not be skipped (deflation
    restarts; the single weighted restart vector used to miss pairs)."""
    import scipy.sparse as sps
    from scipy.sparse.linalg import eigsh

    rng = np.random.default_rng(0)
    n = 1500
    g = sps.random(n, n, density=4e-3, format="csr", dtype=np.float32,
                   random_state=1)
    g = g + g.T
    adj = CSR(g.indptr, g.indices, g.data, g.shape)
    lap = laplacian(adj)
    evals, _ = lanczos_smallest(lap, 6, tol=1e-7)
    ref = np.sort(eigsh(sps.csgraph.laplacian(g).astype(np.float64), k=6,
                        which="SM", return_eigenvectors=False))
    np.testing.assert_allclose(np.sort(np.asarray(evals)), ref, atol=2e-3)


def test_lanczos_rank_deficient_returns_k():
    """A (near-)rank-1 PSD operator must still yield k orthonormal pairs
    (random-complement fill with Rayleigh quotients)."""
    n, k = 200, 3
    rng = np.random.default_rng(1)
    u = rng.random(n).astype(np.float32)
    u /= np.linalg.norm(u)

    def mv(v):
        return 5.0 * u * (u @ v)

    from raft_tpu.sparse.solver.lanczos import _lanczos

    evals, vecs = _lanczos(lambda op, v: mv(v), (), n, k, largest=True)
    assert evals.shape == (k,) and vecs.shape == (n, k)
    assert abs(float(evals[0]) - 5.0) < 1e-3
    # remaining pairs live in the null space with eigenvalue ~0
    np.testing.assert_allclose(np.asarray(evals[1:]), 0.0, atol=1e-3)
    gram = np.asarray(vecs).T @ np.asarray(vecs)
    np.testing.assert_allclose(gram, np.eye(k), atol=1e-3)


def test_lanczos_breakdown_is_relative_to_scale():
    """Invariant-subspace breakdown: a low-rank CSR operator must NOT let
    reorthogonalization noise (~ulp·scale) re-enter as garbage basis vectors
    (regression: absolute tiny**0.5 threshold exploded the recurrence —
    beta grew to ~1e3 on a rank-1 operator of norm 5)."""
    import scipy.sparse as sps

    from raft_tpu.sparse import CSR, lanczos_largest

    n, k = 120, 4
    rng = np.random.default_rng(5)
    u = rng.random(n).astype(np.float32)
    u /= np.linalg.norm(u)
    dense = 5.0 * np.outer(u, u)
    dense[np.abs(dense) < 1e-3] = 0.0  # sparsify
    g = sps.csr_matrix(dense.astype(np.float32))
    a = CSR(g.indptr, g.indices, g.data, g.shape)
    evals, vecs = lanczos_largest(a, k, tol=1e-6)
    top = float(np.asarray(evals)[0])
    ref = float(np.linalg.eigvalsh(g.toarray())[-1])
    assert abs(top - ref) < 1e-2
    # no explosion: every returned eigenvalue bounded by the operator norm
    assert np.all(np.abs(np.asarray(evals)) <= ref * 1.01 + 1e-3)


@pytest.mark.slow  # compile-cache behavior, full solves (tier-1 budget)
def test_lanczos_repeated_solves_share_compiled_program():
    """CSR solves route through the module-level jitted program — repeat
    solves at the same shapes must not retrace (the old per-call closure
    recompiled every solve)."""
    import scipy.sparse as sps

    from raft_tpu.sparse import CSR, laplacian, lanczos_smallest
    from raft_tpu.sparse.solver import lanczos as L

    n = 300
    g = sps.random(n, n, density=0.01, format="csr", dtype=np.float32,
                   random_state=2)
    g = g + g.T
    adj = CSR(g.indptr, g.indices, g.data, g.shape)
    lap = laplacian(adj)
    lanczos_smallest(lap, 3, tol=1e-4)
    traces0 = L._trace_count
    lanczos_smallest(lap, 3, tol=1e-4, seed=1)
    lanczos_smallest(lap, 3, tol=2e-3, seed=2)  # tol is dynamic, no retrace
    assert L._trace_count == traces0


@pytest.mark.slow  # compile-cache behavior, full solves (tier-1 budget)
def test_lanczos_reused_callable_hits_weak_cache():
    """A reused plain matvec callable must reuse its compiled program
    (weak-cached); dropping the callable must release the cache entry."""
    import gc

    from raft_tpu.sparse.solver import lanczos as L

    n = 150
    rng = np.random.default_rng(0)
    M = rng.normal(0, 1, (n, n)).astype(np.float32)
    M = M @ M.T

    def op(v):
        return M @ v

    baseline = len(L._CALLABLE_PROGS)
    L.lanczos_largest(op, 3, n=n)
    traces0 = L._trace_count
    L.lanczos_largest(op, 3, n=n, seed=1)
    assert L._trace_count == traces0
    assert id(op) in L._CALLABLE_PROGS
    del op
    gc.collect()
    assert len(L._CALLABLE_PROGS) == baseline


def test_lanczos_empty_graph_ell():
    """csr_to_ell/spmv path on an all-zero matrix must not crash."""
    from raft_tpu.sparse import csr_to_ell, ell_spmv

    n = 16
    empty = CSR(np.zeros(n + 1, np.int32), np.zeros(0, np.int32),
                np.zeros(0, np.float32), (n, n))
    y = np.asarray(ell_spmv(csr_to_ell(empty), np.ones(n, np.float32)))
    np.testing.assert_allclose(y, 0.0)

@pytest.mark.slow  # compile-cache behavior, full solves (tier-1 budget)
def test_lanczos_bound_method_reuses_program():
    """obj.method creates a fresh bound-method object per attribute access;
    the callable cache must key on (owner, function) so repeated solves with
    the same method hit one compiled program (ADVICE r2)."""
    import gc

    from raft_tpu.sparse.solver import lanczos as L

    n = 150
    rng = np.random.default_rng(3)
    M = rng.normal(0, 1, (n, n)).astype(np.float32)
    M = M @ M.T

    class Op:
        def __init__(self, mat):
            self.mat = mat

        def matvec(self, v):
            return self.mat @ v

    obj = Op(M)
    baseline = len(L._CALLABLE_PROGS)
    L.lanczos_largest(obj.matvec, 3, n=n)
    traces0 = L._trace_count
    L.lanczos_largest(obj.matvec, 3, n=n, seed=1)  # fresh bound-method obj
    assert L._trace_count == traces0
    assert (id(obj), Op.matvec) in L._CALLABLE_PROGS
    del obj
    gc.collect()
    assert len(L._CALLABLE_PROGS) == baseline


def test_lanczos_duplicate_ritz_not_locked_as_spurious():
    """A converged Ritz vector that duplicates an already-locked one leaves
    only ~ulp projected remainder; the relative duplicate threshold must
    reject it instead of normalizing noise into a spurious eigenvector
    (ADVICE r2).  A rank-2 operator with a repeated extremal eigenvalue
    drives the solver into exactly this corner when asked for 3 pairs."""
    from raft_tpu.sparse.solver import lanczos as L

    n = 80
    rng = np.random.default_rng(4)
    q, _ = np.linalg.qr(rng.normal(0, 1, (n, 3)).astype(np.float32))
    # eigenvalues {5, 5, 2}: degenerate top pair, rank-3 operator
    M = (5.0 * np.outer(q[:, 0], q[:, 0]) + 5.0 * np.outer(q[:, 1], q[:, 1])
         + 2.0 * np.outer(q[:, 2], q[:, 2])).astype(np.float32)

    def op(v):
        return M @ v

    vals, vecs = L.lanczos_largest(op, 3, n=n, tol=1e-5)
    vals = np.sort(np.asarray(vals))[::-1]
    assert np.allclose(vals, [5.0, 5.0, 2.0], atol=1e-3)
    # returned vectors must actually be eigenvectors (no normalized noise)
    for i in range(3):
        v = np.asarray(vecs[:, i])
        lam = float(v @ (M @ v))
        assert np.linalg.norm(M @ v - lam * v) < 1e-3


def test_lanczos_triple_degenerate_with_nullspace():
    """Code-review r3 repro: rank-4 operator, spectrum {5,5,5,2,0×76}, k=4.
    An early-locked 0-eigenvector must not displace a missing degenerate
    5-copy — the repair keeps hunting while new directions beat the k-th
    best and the final top-k sort drops the loser."""
    from raft_tpu.sparse.solver import lanczos as L

    n = 80
    rng = np.random.default_rng(7)
    q, _ = np.linalg.qr(rng.normal(0, 1, (n, 4)).astype(np.float32))
    M = sum(lam * np.outer(q[:, i], q[:, i])
            for i, lam in enumerate([5.0, 5.0, 5.0, 2.0]))
    M = M.astype(np.float32)

    def op(v):
        return M @ v

    vals, vecs = L.lanczos_largest(op, 4, n=n, tol=1e-5)
    vals_s = np.sort(np.asarray(vals))[::-1]
    assert np.allclose(vals_s, [5.0, 5.0, 5.0, 2.0], atol=1e-3), vals_s
    vecs_np = np.asarray(vecs)
    for i in range(4):
        v = vecs_np[:, i]
        lam = float(v @ (M @ v))
        assert np.linalg.norm(M @ v - lam * v) < 1e-3


@pytest.mark.parametrize("n,density,seed", [(30, 0.05, 0), (100, 0.02, 1),
                                            (200, 0.005, 2)])
def test_weak_cc_random_grid_vs_scipy(n, density, seed):
    """Component labels on random graphs vs scipy.sparse.csgraph — same
    partition (label values are representative-min ids, so compare up to
    relabeling via ARI == 1)."""
    from raft_tpu.stats import adjusted_rand_index

    rng = np.random.default_rng(seed)
    d = sp.random(n, n, density=density, random_state=rng,
                  format="csr", dtype=np.float32)
    d = ((d + d.T) > 0).astype(np.float32).tocsr()
    labels = np.asarray(weak_cc(to_raft(d)))
    n_comp, want = csgraph.connected_components(d, directed=False)
    assert len(np.unique(labels)) == n_comp
    assert float(adjusted_rand_index(labels, want)) == pytest.approx(1.0)
