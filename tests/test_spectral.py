"""Spectral partition / modularity maximization on planted-community graphs.

Oracle style mirrors reference test/cluster_solvers.cu / test/eigen_solvers.cu
plus property checks: a planted two-block graph must be recovered exactly,
and quality metrics must match hand-computed values.
"""

import numpy as np
import pytest

from raft_tpu.sparse import dense_to_csr
from raft_tpu.spectral import (
    ClusterSolverConfig,
    EigenSolverConfig,
    KMeansClusterSolver,
    LanczosEigenSolver,
    analyze_modularity,
    analyze_partition,
    modularity_maximization,
    partition,
)


def planted_blocks(sizes, p_in=0.8, p_out=0.02, seed=0):
    """Symmetric unweighted block-community adjacency + ground-truth labels."""
    rng = np.random.default_rng(seed)
    n = sum(sizes)
    labels = np.repeat(np.arange(len(sizes)), sizes)
    prob = np.where(labels[:, None] == labels[None, :], p_in, p_out)
    a = (rng.random((n, n)) < prob).astype(np.float32)
    a = np.triu(a, 1)
    # guarantee intra-block connectivity via a path inside each block
    start = 0
    for s in sizes:
        for i in range(start, start + s - 1):
            a[i, i + 1] = 1.0
        start += s
    # one bridge between consecutive blocks so the graph is connected
    start = 0
    for s in sizes[:-1]:
        a[start + s - 1, start + s] = 1.0
        start += s
    a = a + a.T
    return a, labels


def _agree(pred, truth):
    """Fraction of pairs on which two labelings agree (label-permutation
    invariant)."""
    pred, truth = np.asarray(pred), np.asarray(truth)
    same_p = pred[:, None] == pred[None, :]
    same_t = truth[:, None] == truth[None, :]
    return (same_p == same_t).mean()


@pytest.mark.parametrize("sizes", [(30, 30), (25, 25, 25)])
def test_partition_recovers_planted_blocks(sizes):
    a, truth = planted_blocks(sizes, seed=len(sizes))
    k = len(sizes)
    adj = dense_to_csr(a)
    eig = LanczosEigenSolver(EigenSolverConfig(n_eigVecs=k, tol=1e-7))
    km = KMeansClusterSolver(ClusterSolverConfig(n_clusters=k))
    labels, eig_vals, eig_vecs, _ = partition(adj, eig, km)
    assert _agree(labels, truth) > 0.95
    assert eig_vecs.shape == (a.shape[0], k)
    # Laplacian eigenvalues are nonnegative; smallest ~0 (connected graph)
    assert float(eig_vals[0]) < 1e-3
    assert np.all(np.array(eig_vals) > -1e-4)


def test_analyze_partition_matches_dense_oracle():
    a, truth = planted_blocks((20, 20), seed=7)
    adj = dense_to_csr(a)
    edge_cut, cost = analyze_partition(adj, 2, truth)
    # dense oracle
    lap = np.diag(a.sum(1)) - a
    cut = []
    for i in range(2):
        u = (truth == i).astype(np.float64)
        cut.append(u @ lap @ u)
    np.testing.assert_allclose(float(edge_cut), sum(cut) / 2, rtol=1e-5)
    np.testing.assert_allclose(
        float(cost), sum(c / (truth == i).sum() for i, c in enumerate(cut)),
        rtol=1e-5)
    # the planted partition should beat a random one
    rng = np.random.default_rng(0)
    rand_cut, _ = analyze_partition(adj, 2, rng.integers(0, 2, truth.shape[0]))
    assert float(edge_cut) < float(rand_cut)


def test_modularity_maximization_and_analyze():
    a, truth = planted_blocks((30, 30), p_in=0.7, p_out=0.02, seed=3)
    adj = dense_to_csr(a)
    k = 2
    eig = LanczosEigenSolver(EigenSolverConfig(n_eigVecs=k, tol=1e-7))
    km = KMeansClusterSolver(ClusterSolverConfig(n_clusters=k))
    labels, _, _, _ = modularity_maximization(adj, eig, km)
    assert _agree(labels, truth) > 0.95

    q_truth = float(analyze_modularity(adj, 2, truth))
    # dense modularity oracle: Q = (1/2m) Σ_ij (a_ij − d_i d_j / 2m) δ(c_i,c_j)
    d = a.sum(1)
    two_m = d.sum()
    b = a - np.outer(d, d) / two_m
    delta = (truth[:, None] == truth[None, :]).astype(np.float64)
    q_ref = (b * delta).sum() / two_m
    np.testing.assert_allclose(q_truth, q_ref, rtol=1e-5)
    # good community structure → clearly positive modularity
    assert q_truth > 0.3
    # random labels → near-zero modularity
    rng = np.random.default_rng(1)
    q_rand = float(analyze_modularity(adj, 2, rng.integers(0, 2, truth.shape[0])))
    assert q_rand < q_truth / 2
