"""Spectral partition / modularity maximization on planted-community graphs.

Oracle style mirrors reference test/cluster_solvers.cu / test/eigen_solvers.cu
plus property checks: a planted two-block graph must be recovered exactly,
and quality metrics must match hand-computed values.
"""

import numpy as np
import pytest

from raft_tpu.sparse import dense_to_csr
from raft_tpu.spectral import (
    ClusterSolverConfig,
    EigenSolverConfig,
    KMeansClusterSolver,
    LanczosEigenSolver,
    analyze_modularity,
    analyze_partition,
    modularity_maximization,
    partition,
)


def planted_blocks(sizes, p_in=0.8, p_out=0.02, seed=0):
    """Symmetric unweighted block-community adjacency + ground-truth labels."""
    rng = np.random.default_rng(seed)
    n = sum(sizes)
    labels = np.repeat(np.arange(len(sizes)), sizes)
    prob = np.where(labels[:, None] == labels[None, :], p_in, p_out)
    a = (rng.random((n, n)) < prob).astype(np.float32)
    a = np.triu(a, 1)
    # guarantee intra-block connectivity via a path inside each block
    start = 0
    for s in sizes:
        for i in range(start, start + s - 1):
            a[i, i + 1] = 1.0
        start += s
    # one bridge between consecutive blocks so the graph is connected
    start = 0
    for s in sizes[:-1]:
        a[start + s - 1, start + s] = 1.0
        start += s
    a = a + a.T
    return a, labels


def _agree(pred, truth):
    """Fraction of pairs on which two labelings agree (label-permutation
    invariant)."""
    pred, truth = np.asarray(pred), np.asarray(truth)
    same_p = pred[:, None] == pred[None, :]
    same_t = truth[:, None] == truth[None, :]
    return (same_p == same_t).mean()


@pytest.mark.slow  # full eigensolver partitions, ~4s each (tier-1 budget)
@pytest.mark.parametrize("sizes", [(30, 30), (25, 25, 25)])
def test_partition_recovers_planted_blocks(sizes):
    a, truth = planted_blocks(sizes, seed=len(sizes))
    k = len(sizes)
    adj = dense_to_csr(a)
    eig = LanczosEigenSolver(EigenSolverConfig(n_eigVecs=k, tol=1e-7))
    km = KMeansClusterSolver(ClusterSolverConfig(n_clusters=k))
    labels, eig_vals, eig_vecs, _ = partition(adj, eig, km)
    assert _agree(labels, truth) > 0.95
    assert eig_vecs.shape == (a.shape[0], k)
    # Laplacian eigenvalues are nonnegative; smallest ~0 (connected graph)
    assert float(eig_vals[0]) < 1e-3
    assert np.all(np.array(eig_vals) > -1e-4)


def test_analyze_partition_matches_dense_oracle():
    a, truth = planted_blocks((20, 20), seed=7)
    adj = dense_to_csr(a)
    edge_cut, cost = analyze_partition(adj, 2, truth)
    # dense oracle
    lap = np.diag(a.sum(1)) - a
    cut = []
    for i in range(2):
        u = (truth == i).astype(np.float64)
        cut.append(u @ lap @ u)
    np.testing.assert_allclose(float(edge_cut), sum(cut) / 2, rtol=1e-5)
    np.testing.assert_allclose(
        float(cost), sum(c / (truth == i).sum() for i, c in enumerate(cut)),
        rtol=1e-5)
    # the planted partition should beat a random one
    rng = np.random.default_rng(0)
    rand_cut, _ = analyze_partition(adj, 2, rng.integers(0, 2, truth.shape[0]))
    assert float(edge_cut) < float(rand_cut)


def test_modularity_maximization_and_analyze():
    a, truth = planted_blocks((30, 30), p_in=0.7, p_out=0.02, seed=3)
    adj = dense_to_csr(a)
    k = 2
    eig = LanczosEigenSolver(EigenSolverConfig(n_eigVecs=k, tol=1e-7))
    km = KMeansClusterSolver(ClusterSolverConfig(n_clusters=k))
    labels, _, _, _ = modularity_maximization(adj, eig, km)
    assert _agree(labels, truth) > 0.95

    q_truth = float(analyze_modularity(adj, 2, truth))
    # dense modularity oracle: Q = (1/2m) Σ_ij (a_ij − d_i d_j / 2m) δ(c_i,c_j)
    d = a.sum(1)
    two_m = d.sum()
    b = a - np.outer(d, d) / two_m
    delta = (truth[:, None] == truth[None, :]).astype(np.float64)
    q_ref = (b * delta).sum() / two_m
    np.testing.assert_allclose(q_truth, q_ref, rtol=1e-5)
    # good community structure → clearly positive modularity
    assert q_truth > 0.3
    # random labels → near-zero modularity
    rng = np.random.default_rng(1)
    q_rand = float(analyze_modularity(adj, 2, rng.integers(0, 2, truth.shape[0])))
    assert q_rand < q_truth / 2


# ---------------------------------------------------------------------------
# Host-oracle depth (VERDICT r3 #8; shapes mirror reference
# test/eigen_solvers.cu + test/cluster_solvers.cu + spectral_matrix.cu).


def test_laplacian_eigenpairs_match_dense_oracle():
    """LanczosEigenSolver on the implicit Laplacian operator vs
    numpy.linalg.eigh of the dense Laplacian: eigenvalues close, residuals
    ||L v − λ v|| small (eigen_solvers.cu checks its solver the same way)."""
    rng = np.random.default_rng(5)
    n, k = 120, 4
    a = (rng.random((n, n)) < 0.15).astype(np.float32)
    a = np.triu(a, 1)
    a[np.arange(n - 1), np.arange(1, n)] = 1.0   # connect
    w = rng.uniform(0.5, 2.0, (n, n)).astype(np.float32)
    a = (a * w)
    a = a + a.T
    adj = dense_to_csr(a)

    eig = LanczosEigenSolver(EigenSolverConfig(n_eigVecs=k, tol=1e-8, maxIter=60))
    from raft_tpu.spectral.matrix import laplacian_matvec

    mv, deg = laplacian_matvec(adj)
    vals, vecs = eig.solve_smallest_eigenvectors(mv, n=n, dtype=np.float32)
    lap = np.diag(a.sum(1)) - a
    ref = np.linalg.eigvalsh(lap.astype(np.float64))[:k]
    np.testing.assert_allclose(np.array(vals), ref, atol=1e-3)
    v = np.array(vecs)
    res = lap @ v - v * np.array(vals)[None, :]
    assert np.abs(res).max() < 5e-3
    # degrees from the operator builder match the dense row sums
    np.testing.assert_allclose(np.array(deg), a.sum(1), rtol=1e-5)


def test_modularity_operator_matches_dense_oracle():
    """modularity_matvec must implement B·x = A·x − d (dᵀx)/2m exactly
    (spectral_matrix.cu checks the wrapped operators against dense)."""
    rng = np.random.default_rng(9)
    n = 80
    a = (rng.random((n, n)) < 0.2).astype(np.float32)
    a = np.triu(a, 1)
    a = a + a.T
    adj = dense_to_csr(a)
    from raft_tpu.spectral.matrix import modularity_matvec

    mv, deg, edge_sum = modularity_matvec(adj)
    d = a.sum(1)
    two_m = d.sum()
    b = a - np.outer(d, d) / two_m
    for seed in range(3):
        x = np.random.default_rng(seed).normal(0, 1, n).astype(np.float32)
        np.testing.assert_allclose(np.array(mv(x)), b @ x, atol=1e-3)
    np.testing.assert_allclose(float(edge_sum), two_m, rtol=1e-6)


@pytest.mark.slow  # full eigensolver partition (tier-1 budget)
def test_partition_weighted_graph_and_unequal_blocks():
    """Weighted planted partition with unequal block sizes: recovered
    labels and an edge-cut that beats random by a wide margin (the
    cluster_solvers.cu quality ethos)."""
    sizes = (40, 25, 15)
    a, truth = planted_blocks(sizes, p_in=0.7, p_out=0.02, seed=11)
    rng = np.random.default_rng(12)
    w = rng.uniform(1.0, 3.0, a.shape).astype(np.float32)
    w = np.triu(w, 1) + np.triu(w, 1).T
    a = (a * w).astype(np.float32)
    adj = dense_to_csr(a)
    k = len(sizes)
    eig = LanczosEigenSolver(EigenSolverConfig(n_eigVecs=k, tol=1e-7))
    km = KMeansClusterSolver(ClusterSolverConfig(n_clusters=k))
    labels, _, _, _ = partition(adj, eig, km)
    assert _agree(labels, truth) > 0.9
    cut, _ = analyze_partition(adj, k, labels)
    rand_cut, _ = analyze_partition(
        adj, k, np.random.default_rng(1).integers(0, k, a.shape[0]))
    assert float(cut) < 0.5 * float(rand_cut)


@pytest.mark.slow  # hand-oracle over a full modularity solve (budget)
def test_modularity_ring_of_cliques_hand_oracle():
    """Ring of m cliques of size c joined by single edges: the planted
    partition's modularity has a closed form
    Q = (1 − 1/m) − m·k_bridge/(2m_edges)-ish; we compute the dense oracle
    directly and require the maximizer to land on the clique partition."""
    m, c = 6, 8
    n = m * c
    a = np.zeros((n, n), np.float32)
    for b in range(m):
        s = b * c
        blk = slice(s, s + c)
        a[blk, blk] = 1.0
    np.fill_diagonal(a, 0.0)
    for b in range(m):  # ring bridges
        i = b * c
        j = ((b + 1) % m) * c + 1
        a[i, j] = a[j, i] = 1.0
    truth = np.repeat(np.arange(m), c)
    adj = dense_to_csr(a)
    eig = LanczosEigenSolver(EigenSolverConfig(n_eigVecs=m, tol=1e-7, maxIter=60))
    km = KMeansClusterSolver(ClusterSolverConfig(n_clusters=m, seed=4))
    labels, _, _, _ = modularity_maximization(adj, eig, km)
    assert _agree(labels, truth) > 0.95
    # dense modularity oracle for the recovered labels
    d = a.sum(1)
    two_m = d.sum()
    b_mat = a - np.outer(d, d) / two_m
    lab = np.asarray(labels)
    delta = (lab[:, None] == lab[None, :]).astype(np.float64)
    q_ref = (b_mat * delta).sum() / two_m
    q_got = float(analyze_modularity(adj, m, lab))
    np.testing.assert_allclose(q_got, q_ref, rtol=1e-5)
    assert q_got > 0.7   # clique ring has very strong community structure


def test_partition_seed_reproducibility():
    a, _ = planted_blocks((30, 30), seed=21)
    adj = dense_to_csr(a)
    eig = EigenSolverConfig(n_eigVecs=2, tol=1e-7, seed=9)
    km = ClusterSolverConfig(n_clusters=2, seed=9)
    l1, v1, _, _ = partition(adj, LanczosEigenSolver(eig),
                             KMeansClusterSolver(km))
    l2, v2, _, _ = partition(adj, LanczosEigenSolver(eig),
                             KMeansClusterSolver(km))
    np.testing.assert_array_equal(np.array(l1), np.array(l2))
    np.testing.assert_array_equal(np.array(v1), np.array(v2))


def test_analyze_partition_two_components_zero_cut():
    """Labels = connected components ⇒ edge cut exactly 0 (and any mixed
    labeling strictly worse)."""
    a1, _ = planted_blocks((20,), seed=31)
    a2, _ = planted_blocks((25,), seed=32)
    n1, n2 = a1.shape[0], a2.shape[0]
    a = np.zeros((n1 + n2, n1 + n2), np.float32)
    a[:n1, :n1] = a1
    a[n1:, n1:] = a2
    adj = dense_to_csr(a)
    comp = np.concatenate([np.zeros(n1, np.int32), np.ones(n2, np.int32)])
    cut, _ = analyze_partition(adj, 2, comp)
    assert float(cut) == 0.0
    mixed = comp.copy()
    mixed[:3] = 1
    cut_mixed, _ = analyze_partition(adj, 2, mixed)
    assert float(cut_mixed) > 0.0
