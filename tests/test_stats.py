"""Stats tests — counterpart of reference cpp/test/stats/* with sklearn/
numpy oracles (the reference compares against its own naive kernels)."""

import numpy as np
import pytest
import sklearn.metrics as skm

from raft_tpu import stats


@pytest.fixture
def rng():
    return np.random.default_rng(0)


class TestSummary:
    def test_mean_center(self, rng):
        x = rng.standard_normal((100, 5))
        np.testing.assert_allclose(stats.mean(x), x.mean(axis=0), atol=1e-12)
        c = np.asarray(stats.mean_center(x))
        np.testing.assert_allclose(c.mean(axis=0), 0, atol=1e-12)
        np.testing.assert_allclose(stats.mean_add(c, stats.mean(x)), x, atol=1e-12)

    def test_meanvar_stddev(self, rng):
        x = rng.standard_normal((200, 4))
        mu, var = stats.meanvar(x, sample=True)
        np.testing.assert_allclose(mu, x.mean(axis=0), atol=1e-12)
        np.testing.assert_allclose(var, x.var(axis=0, ddof=1), atol=1e-12)
        np.testing.assert_allclose(stats.stddev(x), x.std(axis=0, ddof=1), atol=1e-12)

    def test_sum_cov_minmax(self, rng):
        x = rng.standard_normal((50, 3))
        np.testing.assert_allclose(stats.sum_(x), x.sum(axis=0), atol=1e-12)
        np.testing.assert_allclose(stats.cov(x), np.cov(x.T, ddof=1), atol=1e-10)
        mn, mx = stats.minmax(x)
        np.testing.assert_allclose(mn, x.min(axis=0))
        np.testing.assert_allclose(mx, x.max(axis=0))

    def test_weighted_mean(self, rng):
        x = rng.standard_normal((10, 6))
        w = rng.random(6)
        np.testing.assert_allclose(
            stats.row_weighted_mean(x, w), (x * w).sum(axis=1) / w.sum(), atol=1e-12
        )
        w2 = rng.random(10)
        np.testing.assert_allclose(
            stats.col_weighted_mean(x, w2), (x * w2[:, None]).sum(axis=0) / w2.sum(),
            atol=1e-12,
        )

    def test_histogram(self, rng):
        x = rng.random((1000, 2)).astype(np.float32)
        h = np.asarray(stats.histogram(x, 10, 0.0, 1.0))
        assert h.shape == (10, 2)
        assert h.sum(axis=0).tolist() == [1000, 1000]
        expected = np.histogram(x[:, 0], bins=10, range=(0, 1))[0]
        np.testing.assert_array_equal(h[:, 0], expected)


class TestClassification:
    def test_accuracy(self, rng):
        a = rng.integers(0, 3, 100)
        b = a.copy()
        b[:20] = (b[:20] + 1) % 3
        np.testing.assert_allclose(stats.accuracy(b, a), 0.8, atol=1e-6)

    def test_r2(self, rng):
        y = rng.standard_normal(100)
        yh = y + 0.1 * rng.standard_normal(100)
        np.testing.assert_allclose(stats.r2_score(y, yh), skm.r2_score(y, yh), atol=1e-6)

    def test_regression_metrics(self, rng):
        y = rng.standard_normal(100)
        yh = y + rng.standard_normal(100)
        mae, mse, medae = stats.regression_metrics(yh, y)
        np.testing.assert_allclose(mae, skm.mean_absolute_error(y, yh), atol=1e-9)
        np.testing.assert_allclose(mse, skm.mean_squared_error(y, yh), atol=1e-9)
        np.testing.assert_allclose(medae, skm.median_absolute_error(y, yh), atol=1e-9)


class TestContingency:
    @pytest.fixture
    def labels(self, rng):
        return rng.integers(0, 4, 300), rng.integers(0, 5, 300)

    def test_contingency_matrix(self, labels):
        a, b = labels
        cm = np.asarray(stats.contingency_matrix(a, b, n_classes=5))
        expected = np.zeros((5, 5), int)
        for i, j in zip(a, b):
            expected[i, j] += 1
        np.testing.assert_array_equal(cm, expected)

    def test_entropy(self, labels):
        a, _ = labels
        p = np.bincount(a) / len(a)
        expected = -(p[p > 0] * np.log(p[p > 0])).sum()
        np.testing.assert_allclose(stats.entropy(a), expected, atol=1e-10)

    def test_mutual_info(self, labels):
        a, b = labels
        np.testing.assert_allclose(
            stats.mutual_info_score(a, b), skm.mutual_info_score(a, b), atol=1e-10
        )

    def test_homogeneity_completeness_v(self, labels):
        a, b = labels
        np.testing.assert_allclose(
            stats.homogeneity_score(a, b), skm.homogeneity_score(a, b), atol=1e-8
        )
        np.testing.assert_allclose(
            stats.completeness_score(a, b), skm.completeness_score(a, b), atol=1e-8
        )
        np.testing.assert_allclose(
            stats.v_measure(a, b), skm.v_measure_score(a, b), atol=1e-8
        )

    def test_rand_indices(self, labels):
        a, b = labels
        np.testing.assert_allclose(
            stats.adjusted_rand_index(a, b), skm.adjusted_rand_score(a, b), atol=1e-10
        )
        np.testing.assert_allclose(
            stats.rand_index(a, b), skm.rand_score(a, b), atol=1e-10
        )
        # perfect labeling
        np.testing.assert_allclose(stats.adjusted_rand_index(a, a), 1.0, atol=1e-12)

    def test_kl(self, rng):
        p = rng.random(20)
        p /= p.sum()
        q = rng.random(20)
        q /= q.sum()
        expected = (p * np.log(p / q)).sum()
        np.testing.assert_allclose(stats.kl_divergence(p, q), expected, atol=1e-10)


class TestEmbeddingMetrics:
    def test_silhouette(self, rng):
        from raft_tpu.random import RngState, make_blobs

        x, labels, _ = make_blobs(RngState(1), 300, 8, n_clusters=3, cluster_std=0.5)
        x, labels = np.asarray(x, np.float64), np.asarray(labels)
        got = float(stats.silhouette_score(x, labels))
        expected = skm.silhouette_score(x, labels, metric="sqeuclidean")
        np.testing.assert_allclose(got, expected, atol=1e-5)

    @pytest.mark.slow  # batched-vs-unbatched equivalence (tier-1 budget)
    def test_silhouette_batched_matches(self, rng):
        from raft_tpu.random import RngState, make_blobs

        x, labels, _ = make_blobs(RngState(2), 257, 6, n_clusters=4, cluster_std=0.6)
        x, labels = np.asarray(x, np.float64), np.asarray(labels)
        full = float(stats.silhouette_score(x, labels))
        batched = float(stats.silhouette_score_batched(x, labels, batch_size=100))
        np.testing.assert_allclose(batched, full, atol=1e-10)

    def test_trustworthiness(self, rng):
        x = rng.standard_normal((120, 10))
        # identity embedding → trustworthiness 1; noisy projection < 1
        emb_good = x[:, :10]
        t_good = float(stats.trustworthiness_score(x, emb_good, n_neighbors=5))
        np.testing.assert_allclose(t_good, 1.0, atol=1e-9)
        emb_rand = rng.standard_normal((120, 2))
        t_rand = float(stats.trustworthiness_score(x, emb_rand, n_neighbors=5))
        from sklearn.manifold import trustworthiness as sk_trust

        t_sk = sk_trust(x, np.asarray(emb_rand), n_neighbors=5)
        np.testing.assert_allclose(t_rand, t_sk, atol=1e-6)
        assert t_rand < t_good


class TestDispersionIC:
    def test_dispersion(self, rng):
        centroids = rng.standard_normal((4, 3))
        sizes = np.array([10, 20, 30, 40])
        mu = (centroids * sizes[:, None]).sum(axis=0) / sizes.sum()
        expected = np.sqrt((((centroids - mu) ** 2).sum(axis=1) * sizes).sum())
        np.testing.assert_allclose(
            stats.dispersion(centroids, sizes), expected, atol=1e-10
        )

    def test_information_criterion(self):
        ll = np.array([-100.0, -200.0])
        aic = np.asarray(stats.information_criterion_batched(ll, stats.IC_Type.AIC, 3, 50))
        np.testing.assert_allclose(aic, 2 * 3 - 2 * ll)
        bic = np.asarray(stats.information_criterion_batched(ll, stats.IC_Type.BIC, 3, 50))
        np.testing.assert_allclose(bic, np.log(50) * 3 - 2 * ll)
        aicc = np.asarray(stats.information_criterion_batched(ll, stats.IC_Type.AICc, 3, 50))
        np.testing.assert_allclose(aicc, 2 * (3 + 3 * 4 / (50 - 3 - 1)) - 2 * ll)


class TestDtypeSweep:
    """Reference-style parameterized dtype grid (test/stats/* ValuesIn
    sweeps): summary statistics agree with numpy oracles in both f32 and
    f64 at dtype-appropriate tolerances, and preserve the input dtype."""

    @pytest.mark.parametrize("dtype,tol", [(np.float32, 1e-5),
                                           (np.float64, 1e-12)])
    def test_summary_stats_vs_numpy(self, dtype, tol):
        rng = np.random.default_rng(0)
        x = rng.normal(2.0, 3.0, (257, 19)).astype(dtype)
        np.testing.assert_allclose(np.asarray(stats.mean(x)),
                                   x.mean(axis=0), rtol=tol, atol=tol)
        mu, var = stats.meanvar(x)
        np.testing.assert_allclose(np.asarray(mu), x.mean(axis=0),
                                   rtol=tol, atol=tol)
        np.testing.assert_allclose(np.asarray(var), x.var(axis=0, ddof=1),
                                   rtol=100 * tol, atol=100 * tol)
        np.testing.assert_allclose(np.asarray(stats.cov(x)),
                                   np.cov(x.T), rtol=100 * tol,
                                   atol=100 * tol)
        lo, hi = stats.minmax(x)
        np.testing.assert_array_equal(np.asarray(lo), x.min(axis=0))
        np.testing.assert_array_equal(np.asarray(hi), x.max(axis=0))
        assert np.asarray(stats.mean(x)).dtype == dtype

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_weighted_mean_vs_numpy(self, dtype):
        rng = np.random.default_rng(1)
        x = rng.normal(0, 1, (64, 8)).astype(dtype)
        w = rng.random(8).astype(dtype)
        got = np.asarray(stats.row_weighted_mean(x, w))
        ref = (x * w).sum(axis=1) / w.sum()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


class TestSklearnOracleGrids:
    """Random grids against sklearn/scipy reference implementations —
    stronger than the reference's self-oracles (cpp/test/stats/* compare
    CUDA kernels against naive CUDA kernels; here the oracle is an
    independent library)."""

    @pytest.mark.parametrize("n,k,seed", [(50, 2, 0), (500, 7, 1),
                                          (300, 12, 2)])
    def test_clustering_comparison_metrics(self, n, k, seed):
        r = np.random.default_rng(seed)
        a = r.integers(0, k, n)
        b = np.where(r.random(n) < 0.3, r.integers(0, k, n), a)  # noisy copy
        np.testing.assert_allclose(float(stats.adjusted_rand_index(a, b)),
                                   skm.adjusted_rand_score(a, b), atol=1e-6)
        np.testing.assert_allclose(float(stats.rand_index(a, b)),
                                   skm.rand_score(a, b), atol=1e-6)
        np.testing.assert_allclose(float(stats.mutual_info_score(a, b)),
                                   skm.mutual_info_score(a, b), atol=1e-6)
        np.testing.assert_allclose(float(stats.homogeneity_score(a, b)),
                                   skm.homogeneity_score(a, b), atol=1e-6)
        np.testing.assert_allclose(float(stats.completeness_score(a, b)),
                                   skm.completeness_score(a, b), atol=1e-6)
        np.testing.assert_allclose(float(stats.v_measure(a, b)),
                                   skm.v_measure_score(a, b), atol=1e-6)

    def test_comparison_metrics_relabel_invariant(self):
        """Permuting label IDS must not change any comparison metric."""
        r = np.random.default_rng(3)
        a = r.integers(0, 5, 200)
        b = r.integers(0, 5, 200)
        perm = np.array([3, 0, 4, 1, 2])
        for fn in (stats.adjusted_rand_index, stats.rand_index,
                   stats.mutual_info_score, stats.v_measure):
            np.testing.assert_allclose(float(fn(a, b)), float(fn(perm[a], b)),
                                       atol=1e-6, err_msg=str(fn))

    def test_perfect_and_independent_labelings(self):
        a = np.array([0, 0, 1, 1, 2, 2])
        assert float(stats.adjusted_rand_index(a, a)) == pytest.approx(1.0)
        assert float(stats.v_measure(a, a)) == pytest.approx(1.0)
        # independent labels: ARI concentrates near 0 (can be slightly <0)
        r = np.random.default_rng(4)
        x, y = r.integers(0, 4, 2000), r.integers(0, 4, 2000)
        assert abs(float(stats.adjusted_rand_index(x, y))) < 0.05

    @pytest.mark.parametrize("n,k,d,seed", [(80, 3, 4, 0), (200, 6, 8, 1)])
    def test_silhouette_vs_sklearn(self, n, k, d, seed):
        from raft_tpu.distance import DistanceType

        r = np.random.default_rng(seed)
        x = (r.normal(0, 1, (n, d))
             + 3.0 * r.integers(0, k, n)[:, None]).astype(np.float64)
        labels = r.integers(0, k, n)
        want = skm.silhouette_score(x, labels, metric="euclidean")
        got = float(stats.silhouette_score(
            x, labels, metric=DistanceType.L2SqrtExpanded))
        np.testing.assert_allclose(got, want, atol=1e-5)
        # batched path with a batch smaller than n must agree exactly
        got_b = float(stats.silhouette_score_batched(
            x, labels, metric=DistanceType.L2SqrtExpanded, batch_size=37))
        np.testing.assert_allclose(got_b, want, atol=1e-5)

    @pytest.mark.parametrize("n_neighbors", [3, 5, 12])
    def test_trustworthiness_vs_sklearn(self, n_neighbors):
        from sklearn.manifold import trustworthiness as sk_trust

        r = np.random.default_rng(5)
        x = r.normal(0, 1, (120, 10))
        emb = x[:, :2] + 0.01 * r.normal(0, 1, (120, 2))  # PCA-ish embedding
        want = sk_trust(x, emb, n_neighbors=n_neighbors)
        got = float(stats.trustworthiness_score(x, emb,
                                                n_neighbors=n_neighbors))
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_entropy_vs_scipy(self):
        import scipy.stats as sps

        labels = np.random.default_rng(6).integers(0, 7, 500)
        p = np.bincount(labels) / len(labels)
        np.testing.assert_allclose(float(stats.entropy(labels)),
                                   sps.entropy(p), atol=1e-6)

    def test_kl_divergence_vs_scipy(self):
        import scipy.stats as sps

        r = np.random.default_rng(7)
        p = r.random(32)
        q = r.random(32)
        p, q = p / p.sum(), q / q.sum()
        np.testing.assert_allclose(float(stats.kl_divergence(p, q)),
                                   sps.entropy(p, q), atol=1e-6)

    def test_histogram_grid_vs_numpy(self):
        r = np.random.default_rng(8)
        x = r.normal(0, 2, (5000, 3)).astype(np.float32)
        for n_bins, lo, hi in ((5, -6.0, 6.0), (64, -1.0, 1.0)):
            h = np.asarray(stats.histogram(x, n_bins, lo, hi))
            for j in range(3):
                clipped = np.clip(x[:, j], lo, np.nextafter(hi, lo))
                want = np.histogram(clipped, bins=n_bins, range=(lo, hi))[0]
                np.testing.assert_array_equal(h[:, j], want)

    def test_histogram_auto_range(self):
        """lower/upper omitted: range spans the GLOBAL min/max (reference
        binner default), every sample lands in some bin."""
        r = np.random.default_rng(9)
        x = r.normal(0, 1, (1000, 2))
        h = np.asarray(stats.histogram(x, 16))
        assert h.sum() == 2000

    @pytest.mark.parametrize("sample", [True, False])
    def test_cov_ddof_conventions(self, sample):
        r = np.random.default_rng(10)
        x = r.normal(0, 1, (64, 5))
        got = np.asarray(stats.cov(x, sample=sample))
        want = np.cov(x.T, ddof=1 if sample else 0)
        np.testing.assert_allclose(got, want, atol=1e-10)

    def test_regression_metrics_vs_sklearn(self):
        r = np.random.default_rng(11)
        y = r.normal(0, 1, 256)
        yh = y + 0.3 * r.normal(0, 1, 256)
        np.testing.assert_allclose(float(stats.r2_score(y, yh)),
                                   skm.r2_score(y, yh), atol=1e-6)
        mae, mse, medae = stats.regression_metrics(yh, y)
        np.testing.assert_allclose(mae, skm.mean_absolute_error(y, yh),
                                   atol=1e-6)
        np.testing.assert_allclose(mse, skm.mean_squared_error(y, yh),
                                   atol=1e-6)
        np.testing.assert_allclose(medae, skm.median_absolute_error(y, yh),
                                   atol=1e-6)


def test_silhouette_all_singletons_is_zero():
    """Every point its own cluster: per-sample silhouette is DEFINED as 0
    for singleton clusters (Rousseeuw's convention; sklearn raises here,
    the reference's batched kernel returns the 0 convention)."""
    from raft_tpu.stats import silhouette_score

    x = np.random.default_rng(3).normal(0, 1, (30, 4)).astype(np.float32)
    assert float(silhouette_score(x, np.arange(30, dtype=np.int32), 30)) == 0.0


def test_trustworthiness_identity_embedding_is_one():
    """Embedding == input preserves every neighbourhood: score exactly 1
    (sklearn oracle agrees)."""
    from raft_tpu.stats import trustworthiness_score

    x = np.random.default_rng(3).normal(0, 1, (30, 4)).astype(np.float32)
    assert float(trustworthiness_score(x, x, 5)) == pytest.approx(1.0)
