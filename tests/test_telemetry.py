"""Unified runtime telemetry (ISSUE 9): histogram bucket oracle vs
np.percentile, snapshot/prometheus exporters, disabled-mode no-op identity,
legacy-surface back-compat (the five migrated fragments), span
nesting/exception safety + JSONL sink, the N-thread warmed-ServeEngine
counter-exactness regression, and the sharded-serve snapshot acceptance."""

import io
import json
import pathlib
import re
import sys
import threading

import numpy as np
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from raft_tpu import telemetry  # noqa: E402
from raft_tpu.core.aot import aot_compile_counters  # noqa: E402
from raft_tpu.neighbors import ivf_flat, ivf_pq, knn  # noqa: E402
from raft_tpu.serve import ServeEngine  # noqa: E402


@pytest.fixture
def enabled_telemetry():
    """Force-enable around a test and restore the ambient state."""
    prev = telemetry.set_enabled(True)
    yield
    telemetry.set_enabled(prev)


# ---------------------------------------------------------------------------
# histograms


class TestHistogram:
    def _fill(self, name, samples, reservoir=0):
        h = telemetry.histogram(name, "t", reservoir=reservoir)
        for s in samples:
            h.observe(float(s))
        return h

    def test_quantile_oracle_vs_np_percentile(self, enabled_telemetry):
        """Bucket-boundary oracle: the log-bucket geometry (64 buckets over
        1 µs–100 s, ratio ~×1.33 per bucket) bounds the quantile estimate
        within one bucket ratio of the exact sample quantile, across
        scales from ~100 µs to ~1 s and several distribution widths."""
        rng = np.random.default_rng(0)
        for i, (mu, sigma) in enumerate(
                [(-9, 0.5), (-6, 1.5), (-3, 1.0), (-1, 0.3)]):
            samples = np.exp(rng.normal(mu, sigma, 20000))
            h = self._fill(f"t_hist_oracle_{i}", samples)
            for q in (0.01, 0.25, 0.5, 0.9, 0.99):
                est = h.quantile(q)
                exact = float(np.percentile(samples, q * 100))
                assert exact / 1.34 <= est <= exact * 1.34, \
                    (mu, sigma, q, est, exact)

    def test_bucket_boundaries(self):
        """The bucket index function is exact at its own edges and clamps
        under/overflow into the edge bins (fixed memory, no tails)."""
        assert telemetry.bucket_index(0.0) == 0
        assert telemetry.bucket_index(1e-9) == 0
        assert telemetry.bucket_index(1e9) == telemetry.HIST_BUCKETS - 1
        for i in range(telemetry.HIST_BUCKETS - 1):
            up = telemetry.bucket_upper(i)
            assert telemetry.bucket_index(up * 1.0000001) == i + 1
            assert telemetry.bucket_index(up * 0.9999999) == i
        # monotone edges spanning the documented 1 µs – 100 s range
        assert telemetry.bucket_upper(-1 + 1) > telemetry.HIST_MIN
        assert abs(telemetry.bucket_upper(telemetry.HIST_BUCKETS - 1)
                   - telemetry.HIST_MAX) / telemetry.HIST_MAX < 1e-9

    def test_quantile_clamps_to_observed_range(self, enabled_telemetry):
        h = self._fill("t_hist_clamp", [0.25] * 1000)
        assert h.quantile(0.01) == 0.25
        assert h.quantile(0.99) == 0.25

    def test_empty_quantile_is_none(self):
        h = telemetry.histogram("t_hist_empty", "t")
        assert h.quantile(0.5) is None
        assert h.count() == 0

    def test_reservoir_bounded_and_counts_all(self, enabled_telemetry):
        h = self._fill("t_hist_res", np.linspace(1e-4, 1e-2, 10000),
                       reservoir=256)
        r = h.reservoir()
        assert len(r) == 256  # bounded no matter the observation count
        assert h.count() == 10000
        # uniform reservoir: the sample median should sit near the true one
        assert abs(float(np.median(r)) - 5.05e-3) < 2e-3


# ---------------------------------------------------------------------------
# exporters


class TestExporters:
    def test_snapshot_round_trip(self, enabled_telemetry):
        c = telemetry.counter("t_snap_counter", "help text",
                              labelnames=("kind",))
        c.inc(3, ("a",))
        h = telemetry.histogram("t_snap_hist", "h")
        for v in (1e-4, 2e-4, 5e-3):
            h.observe(v)
        snap = telemetry.snapshot()
        # plain dict, JSON-round-trippable EXACTLY
        assert json.loads(json.dumps(snap)) == snap
        assert snap["t_snap_counter"]["values"]["kind=a"] == 3
        cell = snap["t_snap_hist"]["values"][""]
        assert cell["count"] == 3 and abs(cell["sum"] - 5.3e-3) < 1e-9
        assert cell["min"] == 1e-4 and cell["max"] == 5e-3
        assert sum(n for _, n in cell["buckets"]) == 3
        assert cell["p50"] is not None

    def test_prometheus_text_format(self, enabled_telemetry):
        telemetry.counter("t_prom_counter", "counts things",
                          labelnames=("who",)).inc(2, ('say "hi"\n',))
        h = telemetry.histogram("t_prom_hist", "times things")
        for v in (1e-4, 1e-4, 3e-2):
            h.observe(v)
        text = telemetry.prometheus_text()
        assert "# HELP t_prom_counter counts things" in text
        assert "# TYPE t_prom_counter counter" in text
        assert "# TYPE t_prom_hist histogram" in text
        # label values escaped per the exposition format
        assert 't_prom_counter{who="say \\"hi\\"\\n"} 2' in text
        # cumulative buckets ending at +Inf == _count
        buckets = re.findall(
            r't_prom_hist_bucket\{le="([^"]+)"\} (\d+)', text)
        assert buckets[-1][0] == "+Inf" and buckets[-1][1] == "3"
        counts = [int(n) for _, n in buckets]
        assert counts == sorted(counts), "bucket series must be cumulative"
        finite = [float(le) for le, _ in buckets[:-1]]
        assert finite == sorted(finite)
        assert re.search(r"t_prom_hist_count(\{\})? 3", text)
        assert "t_prom_hist_sum" in text

    def test_exporters_work_while_disabled(self):
        telemetry.counter("t_disabled_counter", "c").inc(1)
        prev = telemetry.set_enabled(False)
        try:
            snap = telemetry.snapshot()
            assert snap["t_disabled_counter"]["values"][""] >= 1
            assert "t_disabled_counter" in telemetry.prometheus_text()
        finally:
            telemetry.set_enabled(prev)


# ---------------------------------------------------------------------------
# spans


class TestSpans:
    def test_nesting_and_jsonl_sink(self, enabled_telemetry):
        sink = io.StringIO()
        telemetry.set_jsonl_sink(sink)
        try:
            with telemetry.span("outer"):
                assert telemetry.current_span() == "outer"
                with telemetry.span("inner"):
                    assert telemetry.current_span() == "inner"
            assert telemetry.current_span() is None
        finally:
            telemetry.set_jsonl_sink(None)
        events = [json.loads(ln) for ln in
                  sink.getvalue().strip().splitlines()]
        # children complete (and therefore emit) before their parents
        assert [e["span"] for e in events] == ["inner", "outer"]
        inner, outer = events
        assert inner["parent"] == "outer" and inner["depth"] == 1
        assert outer["parent"] is None and outer["depth"] == 0
        assert not inner["error"] and inner["dur_s"] >= 0

    def test_exception_safety(self, enabled_telemetry):
        sink = io.StringIO()
        telemetry.set_jsonl_sink(sink)
        before = telemetry.REGISTRY.get(
            "raft_tpu_span_seconds").count(("t_exc",))
        try:
            with pytest.raises(ValueError, match="boom"):
                with telemetry.span("t_exc"):
                    raise ValueError("boom")
        finally:
            telemetry.set_jsonl_sink(None)
        # stack restored, wall time still recorded, error flagged, the
        # exception itself propagated (never swallowed)
        assert telemetry.current_span() is None
        assert telemetry.REGISTRY.get(
            "raft_tpu_span_seconds").count(("t_exc",)) == before + 1
        event = json.loads(sink.getvalue().strip().splitlines()[-1])
        assert event["span"] == "t_exc" and event["error"] is True

    def test_span_records_histogram_and_counter(self, enabled_telemetry):
        with telemetry.span("t_span_rec"):
            pass
        snap = telemetry.snapshot()
        assert snap["raft_tpu_span_total"]["values"]["span=t_span_rec"] == 1
        assert snap["raft_tpu_span_seconds"]["values"][
            "span=t_span_rec"]["count"] == 1

    def test_disabled_span_is_noop(self):
        prev = telemetry.set_enabled(False)
        try:
            with telemetry.span("t_span_off"):
                assert telemetry.current_span() is None  # no stack push
        finally:
            telemetry.set_enabled(prev)
        snap = telemetry.snapshot()
        assert "span=t_span_off" not in snap.get(
            "raft_tpu_span_total", {}).get("values", {})

    def test_threads_have_independent_stacks(self, enabled_telemetry):
        seen = {}

        def worker():
            with telemetry.span("t_thread_inner"):
                seen["inner"] = telemetry.current_span()

        with telemetry.span("t_thread_outer"):
            th = threading.Thread(target=worker)
            th.start()
            th.join()
            assert telemetry.current_span() == "t_thread_outer"
        assert seen["inner"] == "t_thread_inner"


# ---------------------------------------------------------------------------
# disabled-mode identity + legacy surfaces


_N, _DIM, _K = 400, 16, 5


def _data():
    rng = np.random.default_rng(3)
    x = rng.normal(0, 1, (_N, _DIM)).astype(np.float32)
    q = rng.normal(0, 1, (17, _DIM)).astype(np.float32)
    return x, q


def test_disabled_mode_identity():
    """RAFT_TPU_TELEMETRY=0 must be a pure observability switch: search
    results (brute force, ivf_flat, and a coalesced ServeEngine replay)
    are bit-identical with telemetry on vs off."""
    x, q = _data()
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=8,
                                                kmeans_n_iters=4), x)
    eng = ServeEngine(x, _K, max_batch=32)
    eng.warmup()
    reqs = [q[:3], q[3:10], q[10:]]

    def run_all():
        d1, i1 = knn(x, q, _K)
        d2, i2 = ivf_flat.search(ivf_flat.SearchParams(n_probes=4),
                                 index, q, _K)
        serve = eng.search(reqs)
        return ([np.asarray(a) for a in (d1, i1, d2, i2)],
                [(np.asarray(d), np.asarray(i)) for d, i in serve])

    prev = telemetry.set_enabled(True)
    try:
        on_solo, on_serve = run_all()
        telemetry.set_enabled(False)
        off_solo, off_serve = run_all()
    finally:
        telemetry.set_enabled(prev)
    for a, b in zip(on_solo, off_solo):
        np.testing.assert_array_equal(a, b)
    for (da, ia), (db, ib) in zip(on_serve, off_serve):
        np.testing.assert_array_equal(da, db)
        np.testing.assert_array_equal(ia, ib)


def test_disabled_mode_keeps_contract_counters_live():
    """The legacy counters are contract instruments (zero-compile serve
    gates, LUT trace asserts) — they keep counting with telemetry off."""
    prev = telemetry.set_enabled(False)
    try:
        c0 = aot_compile_counters["compiles"]
        from raft_tpu.core.aot import aot

        f = aot(lambda v: v + 1)
        f(jnp.zeros((4,)))
        assert aot_compile_counters["compiles"] == c0 + 1
        # ...but histograms/reservoirs do NOT record
        h = telemetry.histogram("t_disabled_hist", "h")
        h.observe(1.0)
        assert h.count() == 0
    finally:
        telemetry.set_enabled(prev)


def _disabled_dispatch_probe(v):
    return v * 2


def test_disabled_mode_keeps_dispatch_counter_live():
    """ISSUE 10 satellite regression: ``record_dispatch`` used to drop the
    whole record — including ``raft_tpu_aot_dispatch_total`` — under
    RAFT_TPU_TELEMETRY=0, violating the module contract that COUNTERS stay
    live (warm/cold dispatch totals back zero-compile gates).  Only the
    latency-histogram observe may be gated."""
    from raft_tpu.core.aot import aot

    prev = telemetry.set_enabled(False)
    try:
        f = aot(_disabled_dispatch_probe)
        x = jnp.zeros((8,))
        f(x)  # cold
        f(x)  # warm
        f(x)  # warm
        snap = telemetry.snapshot()
        disp = snap["raft_tpu_aot_dispatch_total"]["values"]
        assert disp.get("fn=_disabled_dispatch_probe,temp=cold") == 1, disp
        assert disp.get("fn=_disabled_dispatch_probe,temp=warm") == 2, disp
        # ...while the per-signature latency HISTOGRAM stayed silent
        lat = snap.get("raft_tpu_aot_dispatch_seconds",
                       {}).get("values", {})
        assert not any(k.startswith("fn=_disabled_dispatch_probe")
                       for k in lat), lat
    finally:
        telemetry.set_enabled(prev)


class TestLegacySurfaces:
    def test_counter_view_reads_like_a_counter(self):
        v = telemetry.legacy_counter("t_legacy_view", "t")
        assert v["missing"] == 0  # Counter contract: missing → 0
        v.inc("a")
        v.inc("a")
        v.inc("b", 10)
        v["c"] = 7  # absolute item assignment still works
        assert v["a"] == 2 and v["b"] == 10 and v["c"] == 7
        assert dict(v) == {"a": 2, "b": 10, "c": 7}
        assert sorted(v) == ["a", "b", "c"] and len(v) == 3
        assert v.get("a", 0) == 2 and v.get("zz", 5) == 5
        # the snapshot-and-diff idiom every counter-assert test uses
        before = dict(v)
        v.inc("a")
        delta = {k: v[k] - before.get(k, 0) for k in v
                 if v[k] != before.get(k, 0)}
        assert delta == {"a": 1}

    def test_aot_compile_counters_is_registry_backed(self):
        assert isinstance(aot_compile_counters, telemetry.LegacyCounterView)
        assert "raft_tpu_aot_compiles" in telemetry.snapshot()

    def test_lut_and_build_trace_counters_registry_backed(self):
        from raft_tpu.neighbors._build import build_trace_counters

        assert isinstance(ivf_pq.lut_trace_counters,
                          telemetry.LegacyCounterView)
        assert isinstance(build_trace_counters, telemetry.LegacyCounterView)

    def test_comms_views_are_per_instance(self):
        from jax.sharding import Mesh
        from raft_tpu.comms import build_comms

        mesh = Mesh(np.array(jax.devices()[:1]), ("world",))
        a, b = build_comms(mesh), build_comms(mesh)
        before_b = dict(b.collective_calls)
        a.collective_calls.inc("allreduce")
        a.collective_calls.inc("allreduce_bytes", 4096)
        assert a.collective_calls["allreduce"] == 1
        assert dict(b.collective_calls) == before_b, \
            "instance views must not alias"
        # ...while the registry aggregates across instances
        snap = telemetry.snapshot()
        vals = snap["raft_tpu_comms_collective_calls"]["values"]
        assert any(k.endswith("key=allreduce_bytes") for k in vals)

    def test_serve_stats_reads_like_the_old_dict(self):
        x, q = _data()
        eng = ServeEngine(x, _K, max_batch=32)
        # the pre-telemetry key set still reads zero at construction; the
        # failure-model keys (ISSUE 14) and the scheduler/replica keys
        # (ISSUE 15) extend the same dict surface
        assert dict(eng.stats) == {
            "requests": 0, "queries": 0, "super_batches": 0,
            "solo_fallbacks": 0, "coalesced_requests": 0, "refreshes": 0,
            "admitted": 0, "sheds": 0, "expired": 0, "retries": 0,
            "watchdog_timeouts": 0, "isolation_splits": 0,
            "ingest_errors": 0, "dispatch_errors": 0,
            "sched_dispatches": 0, "sched_waits": 0,
            "replica_faults": 0, "replica_reroutes": 0}
        eng.warmup()
        eng.search([q[:2], q[2:5]])
        assert eng.stats["requests"] == 2
        assert eng.stats["queries"] == 5
        assert eng.stats["super_batches"] == 1

    def test_last_latencies_bounded(self):
        from raft_tpu.serve.engine import LATENCY_RESERVOIR

        x, q = _data()
        eng = ServeEngine(x, _K, max_batch=32)
        eng.warmup()
        eng.search([q[:2], q[2:4], q[4:5]])
        lat = eng.last_latencies
        assert len(lat) == 3 and all(t >= 0.0 for t in lat)
        assert LATENCY_RESERVOIR == 4096
        # the histogram carries the full distribution for quantile reads
        prev = telemetry.set_enabled(True)
        try:
            eng.search([q[:2]])
            p50, p99 = eng.latency_quantiles((0.5, 0.99))
            assert p50 is not None and p99 is not None and p99 >= p50 > 0
        finally:
            telemetry.set_enabled(prev)


# ---------------------------------------------------------------------------
# thread-safety regression (satellite: the Counter read-modify-write race)


class TestThreadSafety:
    def test_counter_inc_exact_under_contention(self):
        v = telemetry.legacy_counter("t_hammer_counter", "t")
        n_threads, n_inc = 8, 20000

        def worker():
            for _ in range(n_inc):
                v.inc("hits")

        threads = [threading.Thread(target=worker)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert v["hits"] == n_threads * n_inc  # EXACT, no lost updates

    def test_warmed_engine_hammered_from_threads(self):
        """Satellite regression: N threads hammer a WARMED ServeEngine;
        every counter total must be exact (requests/queries/super-batches)
        and the steady state must stay zero-compile — the old plain-dict /
        Counter storage could lose increments under this load."""
        x, q = _data()
        eng = ServeEngine(x, _K, max_batch=32)
        eng.warmup()
        reqs = [q[:3], q[3:8]]
        eng.search(reqs)  # plumbing warm call
        base = dict(eng.stats)
        c0 = aot_compile_counters["compiles"]
        n_threads, n_calls = 6, 8
        errs = []

        def worker():
            try:
                for _ in range(n_calls):
                    out = eng.search(reqs)
                    assert len(out) == 2
            except Exception as e:  # surfaced below, not swallowed
                errs.append(e)

        threads = [threading.Thread(target=worker)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        total_calls = n_threads * n_calls
        assert eng.stats["requests"] - base["requests"] == 2 * total_calls
        assert eng.stats["queries"] - base["queries"] == 8 * total_calls
        assert (eng.stats["super_batches"] - base["super_batches"]
                == total_calls)
        assert aot_compile_counters["compiles"] == c0, \
            "warmed engine must not compile under concurrent serving"


# ---------------------------------------------------------------------------
# acceptance: snapshot after a warmed sharded serve replay


def test_snapshot_after_warmed_sharded_serve(enabled_telemetry):
    """ISSUE 9 acceptance: after a warmed sharded serve replay the
    snapshot carries (a) serve latency histograms, (b) per-program AOT
    dispatch counts, (c) collective byte totals."""
    from jax.sharding import Mesh
    from raft_tpu.comms import build_comms

    x, q = _data()
    comms = build_comms(Mesh(np.array(jax.devices()[:1]), ("world",)))
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=8,
                                                kmeans_n_iters=4), x)
    sharded = index.shard(comms)
    eng = ServeEngine(sharded, _K, ivf_flat.SearchParams(n_probes=4),
                      max_batch=32)
    eng.warmup()
    eng.search([q[:3], q[3:9], q[9:]])
    snap = telemetry.snapshot()

    lat = snap["raft_tpu_serve_request_latency_seconds"]["values"]
    assert any(cell["count"] >= 3 for cell in lat.values()), lat
    dispatch = snap["raft_tpu_aot_dispatch_total"]["values"]
    assert any("temp=warm" in k for k in dispatch), dispatch
    coll = snap["raft_tpu_comms_collective_calls"]["values"]
    assert any("key=allgather_bytes" in k and v > 0
               for k, v in coll.items()), coll
    # and the prometheus rendering of the same state is non-trivial
    text = telemetry.prometheus_text()
    assert "raft_tpu_serve_request_latency_seconds_bucket" in text
