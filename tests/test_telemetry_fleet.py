"""Device-cost attribution, fleet aggregation, and the live scrape
surface (ISSUE 10): program cost gauges for every registered hot path,
device-time sampling, exact histogram merge property tests vs the union
stream, host-plane ``gather``, Prometheus round-trip through the HTTP
endpoints, and the slow-request flight recorder."""

import json
import pathlib
import re
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from raft_tpu import telemetry  # noqa: E402
from raft_tpu.serve import ServeEngine  # noqa: E402
from raft_tpu.telemetry import aggregate  # noqa: E402
from raft_tpu.telemetry import http as telemetry_http  # noqa: E402
from raft_tpu.telemetry.export import snapshot as _snapshot  # noqa: E402
from raft_tpu.telemetry.registry import Registry  # noqa: E402


@pytest.fixture
def enabled_telemetry():
    prev = telemetry.set_enabled(True)
    yield
    telemetry.set_enabled(prev)


@pytest.fixture
def sample_every_4():
    prev = telemetry.set_sample_every(4)
    yield
    telemetry.set_sample_every(prev)


# ---------------------------------------------------------------------------
# device-cost attribution


def _fleet_probe_matmul(a, b):
    return a @ b


# module-level so each AotFunction's fn label (its __qualname__) is the
# bare name the assertions key on
def _fleet_probe_sampled(v):
    return v * 3 + 1


def _fleet_probe_off(v):
    return v - 2


def _fleet_probe_zero(v):
    return v + 7


class TestDeviceAttribution:
    def test_compile_harvests_program_gauges(self, enabled_telemetry):
        from raft_tpu.core.aot import aot

        f = aot(_fleet_probe_matmul)
        a = jnp.ones((64, 32), jnp.float32)
        b = jnp.ones((32, 16), jnp.float32)
        f(a, b)
        snap = telemetry.snapshot()
        flops = {k: v for k, v in
                 snap["raft_tpu_program_flops"]["values"].items()
                 if k.startswith("fn=_fleet_probe_matmul,")}
        assert flops, snap["raft_tpu_program_flops"]["values"]
        # 2·m·n·k FLOPs for the matmul, exactly what cost_analysis reports
        assert list(flops.values())[0] == pytest.approx(2 * 64 * 32 * 16)
        nbytes = {k: v for k, v in
                  snap["raft_tpu_program_bytes_accessed"]["values"].items()
                  if k.startswith("fn=_fleet_probe_matmul,")}
        assert nbytes and list(nbytes.values())[0] > 0

    def test_warm_dispatch_sampling_populates_device_seconds(
            self, enabled_telemetry, sample_every_4):
        from raft_tpu.core.aot import aot

        f = aot(_fleet_probe_sampled)
        x = jnp.ones((256,))
        for _ in range(9):  # 1 cold + 8 warm → samples at warm #1 and #5
            f(x)
        hist = telemetry.REGISTRY.get("raft_tpu_device_seconds")
        count = hist.count(("_fleet_probe_sampled",))
        assert count == 2, count
        # achieved-rate gauges derive from the static (fn, sig) costs
        rate = telemetry.REGISTRY.get("raft_tpu_device_bytes_per_second")
        assert rate.get(("_fleet_probe_sampled",)) > 0

    def test_sampling_disabled_with_telemetry_off(self, sample_every_4):
        from raft_tpu.core.aot import aot

        f = aot(_fleet_probe_off)
        x = jnp.ones((64,))
        prev = telemetry.set_enabled(False)
        try:
            for _ in range(8):
                f(x)
        finally:
            telemetry.set_enabled(prev)
        hist = telemetry.REGISTRY.get("raft_tpu_device_seconds")
        assert hist is None or hist.count(("_fleet_probe_off",)) == 0

    def test_sample_every_zero_disables(self, enabled_telemetry):
        from raft_tpu.core.aot import aot

        prev = telemetry.set_sample_every(0)
        try:
            f = aot(_fleet_probe_zero)
            x = jnp.ones((64,))
            for _ in range(6):
                f(x)
        finally:
            telemetry.set_sample_every(prev)
        hist = telemetry.REGISTRY.get("raft_tpu_device_seconds")
        assert hist is None or hist.count(("_fleet_probe_zero",)) == 0


@pytest.mark.slow  # compiles all 13 registered programs (~30s cold);
# ci/checks.sh --hlo --strict verifies the full registry every run
def test_all_registered_hot_paths_report_cost_gauges(enabled_telemetry):
    """ISSUE 10 acceptance: every @hlo_program-registered hot path (all
    nine at HEAD) reports flops AND bytes-accessed gauges — the audit
    harvest and the live gauges are the same cost_analysis call."""
    from raft_tpu.analysis import hlo_audit
    from raft_tpu.analysis import registry as hlo_registry

    entries = hlo_registry.iter_programs()
    assert len(entries) >= 9, [e.name for e in entries]
    for e in entries:
        r = hlo_audit.audit_program(e)
        assert r.status == "ok", (e.name, r.status, r.findings)
    snap = telemetry.snapshot()
    flops = snap["raft_tpu_program_flops"]["values"]
    nbytes = snap["raft_tpu_program_bytes_accessed"]["values"]
    for e in entries:
        key = f"fn={e.name},sig=audit"
        assert flops.get(key, 0) > 0, (e.name, key)
        assert nbytes.get(key, 0) > 0, (e.name, key)


# ---------------------------------------------------------------------------
# fleet aggregation: merge + gather


def _shard_streams(rng, n_shards):
    """Heterogeneous per-shard latency streams across the histogram's
    whole scale (plus under/overflow clamp traffic)."""
    streams = []
    for s in range(n_shards):
        mu = rng.uniform(-10, -1)
        vals = np.exp(rng.normal(mu, 1.2, rng.integers(200, 2000)))
        if s == 0:  # edge-bin clamps ride along
            vals = np.concatenate([vals, [1e-9, 500.0]])
        streams.append(vals)
    return streams


class TestMerge:
    def test_merge_equals_union_stream(self, enabled_telemetry):
        """Property: merging per-shard snapshots is bucket-exact vs ONE
        histogram observing the union stream — same counts per bucket,
        same _count, min/max folded, _sum to float-reassociation."""
        rng = np.random.default_rng(11)
        streams = _shard_streams(rng, 5)
        shard_snaps = []
        for vals in streams:
            reg = Registry()
            h = reg.histogram("t_fleet_lat", "t", labelnames=("shard",))
            for v in vals:
                h.observe(float(v), ("s",))
            reg.counter("t_fleet_reqs", "t").inc(len(vals))
            shard_snaps.append(_snapshot(registry=reg))
        merged = aggregate.merge(shard_snaps)

        union_reg = Registry()
        hu = union_reg.histogram("t_fleet_lat", "t", labelnames=("shard",))
        for vals in streams:
            for v in vals:
                hu.observe(float(v), ("s",))
        union = _snapshot(registry=union_reg)

        mcell = merged["t_fleet_lat"]["values"]["shard=s"]
        ucell = union["t_fleet_lat"]["values"]["shard=s"]
        assert mcell["buckets"] == ucell["buckets"]  # bucket-wise EXACT
        assert mcell["count"] == ucell["count"]
        assert mcell["min"] == ucell["min"]
        assert mcell["max"] == ucell["max"]
        assert mcell["sum"] == pytest.approx(ucell["sum"], rel=1e-12)
        # counters sum exactly
        assert merged["t_fleet_reqs"]["values"][""] == sum(
            len(v) for v in streams)

    def test_merged_quantile_tracks_np_percentile(self, enabled_telemetry):
        """Property: p50/p99 of the merged cell stay within one bucket
        ratio (~x1.33, same oracle style as PR 9) of np.percentile over
        the union of all shard samples."""
        rng = np.random.default_rng(23)
        for trial in range(4):
            streams = _shard_streams(rng, rng.integers(2, 7))
            snaps = []
            for vals in streams:
                reg = Registry()
                h = reg.histogram("t_fleet_q", "t")
                for v in vals:
                    h.observe(float(v))
                snaps.append(_snapshot(registry=reg))
            cell = aggregate.merge(snaps)["t_fleet_q"]["values"][""]
            allv = np.concatenate(streams)
            # clamp the oracle into the histogram's representable range —
            # the under/overflow traffic lands in the edge bins by design
            allv = np.clip(allv, telemetry.HIST_MIN, telemetry.HIST_MAX)
            for q, est in ((0.5, cell["p50"]), (0.99, cell["p99"])):
                exact = float(np.percentile(allv, q * 100))
                assert exact / 1.34 <= est <= exact * 1.34, \
                    (trial, q, est, exact)

    def test_gauge_and_label_union(self, enabled_telemetry):
        ra, rb = Registry(), Registry()
        ra.gauge("t_fleet_g", "t", ("fn",)).set(5.0, ("a",))
        rb.gauge("t_fleet_g", "t", ("fn",)).set(9.0, ("a",))
        rb.gauge("t_fleet_g", "t", ("fn",)).set(2.0, ("b",))
        m = aggregate.merge([_snapshot(registry=ra),
                             _snapshot(registry=rb)])
        assert m["t_fleet_g"]["values"] == {"fn=a": 9.0, "fn=b": 2.0}

    def test_type_mismatch_raises(self, enabled_telemetry):
        ra, rb = Registry(), Registry()
        ra.counter("t_fleet_clash", "t").inc(1)
        rb.gauge("t_fleet_clash", "t").set(1.0)
        with pytest.raises(ValueError, match="disagrees"):
            aggregate.merge([_snapshot(registry=ra),
                             _snapshot(registry=rb)])

    def test_merge_output_is_json_safe(self, enabled_telemetry):
        reg = Registry()
        h = reg.histogram("t_fleet_json", "t")
        h.observe(1e-3)
        m = aggregate.merge([_snapshot(registry=reg)])
        assert json.loads(json.dumps(m)) == m


class TestGather:
    def test_single_host_gather(self, enabled_telemetry):
        from jax.sharding import Mesh
        from raft_tpu.comms import build_comms

        comms = build_comms(Mesh(np.array(jax.devices()[:1]), ("world",)))
        comms.collective_calls.inc("allreduce")
        comms.collective_calls.inc("allreduce_bytes", 4096)
        fleet = telemetry.gather(comms)
        assert fleet["world"] == 1 and set(fleet["hosts"]) == {"0"}
        roll = fleet["rollup"]["raft_tpu_comms_collective_calls"]["values"]
        prefix = ",".join(
            f"comm={v}" for v in comms.collective_calls.fixed_labels)
        assert roll[f"{prefix},key=allreduce"] == 1
        assert roll[f"{prefix},key=allreduce_bytes"] == 4096

    def test_two_host_gather_over_the_mailbox_plane(self,
                                                    enabled_telemetry):
        """Two host 'processes' (rank 0/1 communicators over the process-
        local mailbox plane, the CI-feasible stand-in for DCN) gather
        concurrently; both get the same symmetric fleet view and the
        rollup sums both hosts' counter reads."""
        from jax.sharding import Mesh
        from raft_tpu.comms.comms import Comms

        mesh = Mesh(np.array(jax.devices()[:1]), ("world",))
        c0 = Comms(mesh, session_id="t-fleet-gather", host_rank=0,
                   host_world=2)
        c1 = Comms(mesh, session_id="t-fleet-gather", host_rank=1,
                   host_world=2)
        marker = telemetry.counter("t_fleet_gather_marker")
        marker.inc(3)
        fleets, errs = {}, []

        def run(rank, comms):
            try:
                fleets[rank] = telemetry.gather(comms, timeout=30.0)
            except Exception as e:  # surfaced below
                errs.append(e)

        threads = [threading.Thread(target=run, args=(r, c))
                   for r, c in ((0, c0), (1, c1))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errs, errs
        for rank in (0, 1):
            fleet = fleets[rank]
            assert fleet["world"] == 2
            assert set(fleet["hosts"]) == {"0", "1"}
            assert fleet["partial"] is False
            assert fleet["missing_ranks"] == []
            # both hosts run in ONE test process sharing one registry, so
            # the rollup counter is the marker counted once per host view
            assert fleet["rollup"]["t_fleet_gather_marker"]["values"][
                ""] == 2 * marker.get()

    def test_dead_host_degrades_to_partial_rollup(self, enabled_telemetry):
        """ISSUE 14 satellite: a dead/slow host must NOT turn the fleet
        rollup into a waitall timeout for every rank — gather degrades to
        a partial rollup listing missing_ranks, the present hosts' rows
        merge, and the communicator's data-plane clique is NOT poisoned
        (a failed telemetry exchange is not a broken compute plane)."""
        from jax.sharding import Mesh
        from raft_tpu.comms.comms import Comms

        mesh = Mesh(np.array(jax.devices()[:1]), ("world",))
        # world CLAIMS three host ranks; rank 2 never shows up (dead host)
        c0 = Comms(mesh, session_id="t-fleet-partial", host_rank=0,
                   host_world=3)
        c1 = Comms(mesh, session_id="t-fleet-partial", host_rank=1,
                   host_world=3)
        fleets, errs = {}, []

        def run(rank, comms):
            try:
                fleets[rank] = telemetry.gather(comms, timeout=1.5)
            except Exception as e:  # surfaced below
                errs.append(e)

        threads = [threading.Thread(target=run, args=(r, c))
                   for r, c in ((0, c0), (1, c1))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errs, errs
        for rank, comms in ((0, c0), (1, c1)):
            fleet = fleets[rank]
            assert fleet["partial"] is True
            assert fleet["missing_ranks"] == [2]
            assert set(fleet["hosts"]) == {"0", "1"}
            assert "rollup" in fleet and fleet["world"] == 3
            # the observability plane must not poison the compute plane
            assert comms._aborted is False

    def test_strict_gather_still_raises(self, enabled_telemetry):
        from jax.sharding import Mesh
        from raft_tpu.comms.comms import Comms
        from raft_tpu.core.error import LogicError

        mesh = Mesh(np.array(jax.devices()[:1]), ("world",))
        c0 = Comms(mesh, session_id="t-fleet-strict", host_rank=0,
                   host_world=2)
        with pytest.raises(LogicError):
            telemetry.gather(c0, timeout=0.2, strict=True)


# ---------------------------------------------------------------------------
# the live scrape surface


#: prometheus text exposition grammar (the round-trip parser): comment
#: lines and sample lines `name{labels} value`
_PROM_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(?:[^"}]*"(?:[^"\\]|\\.)*")*[^}]*\})? '
    r'(\S+)$')
_PROM_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_prometheus(text):
    """Validate + parse a text-exposition body: every line must be a
    HELP/TYPE comment or a sample; returns {name: {label_str: value}} and
    {name: type}."""
    samples, types = {}, {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            m = re.match(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$",
                         line)
            assert m, f"malformed comment line: {line!r}"
            if m.group(1) == "TYPE":
                _, _, name, kind = line.split(" ", 3)
                types[name] = kind
            continue
        m = _PROM_SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        for lm in _PROM_LABEL_RE.finditer(labels):
            assert lm.group(1)  # label names parse
        samples.setdefault(name, {})[labels] = float(value)
    return samples, types


class TestScrapeServer:
    def _get(self, url):
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.read().decode()

    def test_metrics_round_trip(self, enabled_telemetry):
        """Acceptance: /metrics parses as valid Prometheus text exposition
        and round-trips known values from the snapshot."""
        c = telemetry.counter("t_fleet_scrape_total", "scrapes",
                              labelnames=("who",))
        c.inc(7, ("op \"x\"",))
        h = telemetry.histogram("t_fleet_scrape_lat", "lat")
        for v in (1e-4, 2e-3, 0.5):
            h.observe(v)
        with telemetry_http.TelemetryServer(0) as srv:
            text = self._get(srv.url + "/metrics")
        samples, types = _parse_prometheus(text)
        assert types["t_fleet_scrape_total"] == "counter"
        assert types["t_fleet_scrape_lat"] == "histogram"
        assert samples["t_fleet_scrape_total"][
            '{who="op \\"x\\""}'] == 7
        # histogram invariants: cumulative buckets ending at +Inf == count
        buckets = samples["t_fleet_scrape_lat_bucket"]
        series = sorted(
            ((float("inf") if 'le="+Inf"' in k
              else float(_PROM_LABEL_RE.search(k).group(2))), v)
            for k, v in buckets.items())
        counts = [v for _, v in series]
        assert counts == sorted(counts) and counts[-1] == 3
        assert samples["t_fleet_scrape_lat_count"][""] == 3
        assert samples["t_fleet_scrape_lat_sum"][""] == pytest.approx(
            0.5021, rel=1e-3)
        # and the same state via the snapshot agrees
        snap = telemetry.snapshot()
        assert snap["t_fleet_scrape_lat"]["values"][""]["count"] == 3

    def test_varz_and_debug_slow_default(self, enabled_telemetry):
        telemetry.counter("t_fleet_varz_probe").inc(2)
        with telemetry_http.TelemetryServer(0) as srv:
            varz = json.loads(self._get(srv.url + "/varz"))
            slow = json.loads(self._get(srv.url + "/debug/slow"))
            try:
                self._get(srv.url + "/nope")
                assert False, "unknown path must 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        assert varz["t_fleet_varz_probe"]["values"][""] >= 2
        assert slow["entries"] == [] and slow["recorded"] == 0

    def test_healthz_reflects_engine_readiness(self, enabled_telemetry):
        rng = np.random.default_rng(0)
        x = rng.random((300, 16), dtype=np.float32)
        eng = ServeEngine(x, 4, max_batch=32)
        srv = eng.serve_http(0)
        try:
            assert eng.serve_http(0) is srv  # idempotent
            try:
                self._get(srv.url + "/healthz")
                assert False, "unwarmed engine must 503"
            except urllib.error.HTTPError as e:
                assert e.code == 503
                body = json.loads(e.read())
                assert body["ready"] is False
            eng.warmup()
            health = json.loads(self._get(srv.url + "/healthz"))
            assert health["ready"] is True
            assert health["warmed"] and health["backend"] == "brute_force"
            assert health["refresh_in_flight"] is False
        finally:
            eng.close()

    def test_flight_recorder_captures_slow_request_tree(
            self, enabled_telemetry):
        rng = np.random.default_rng(1)
        x = rng.random((300, 16), dtype=np.float32)
        q = rng.random((9, 16), dtype=np.float32)
        eng = ServeEngine(x, 4, max_batch=32)
        eng.warmup()
        srv = eng.serve_http(0, slow_threshold_s=0.0)  # everything is slow
        try:
            eng.search([q[:3], q[3:]])
            slow = json.loads(self._get(srv.url + "/debug/slow"))
            assert slow["recorded"] >= 1
            entry = slow["entries"][-1]
            assert entry["requests"] == 2 and entry["queries"] == 9
            roots = entry["spans"]
            assert [n["span"] for n in roots] == ["serve.request"]
            children = [c["span"] for c in roots[0]["children"]]
            assert children[0] == "serve.ingest"
            assert "serve.dispatch" in children
            assert "serve.deliver" in children
        finally:
            eng.close()

    def test_fast_requests_not_recorded(self, enabled_telemetry):
        rng = np.random.default_rng(2)
        x = rng.random((300, 16), dtype=np.float32)
        q = rng.random((4, 16), dtype=np.float32)
        eng = ServeEngine(x, 4, max_batch=32)
        eng.warmup()
        eng.serve_http(0, slow_threshold_s=1e9)  # nothing is slow
        try:
            eng.search([q])
            assert eng._recorder.seen == 0
        finally:
            eng.close()


def test_flight_recorder_ring_is_bounded():
    rec = telemetry_http.FlightRecorder(threshold_s=0.0, cap=8)
    for i in range(100):
        rec.record([], dur_s=float(i))
    entries = rec.entries()
    assert len(entries) == 8 and rec.seen == 100
    assert [e["dur_s"] for e in entries] == [float(i) for i in range(92, 100)]
    view = rec.view()
    assert view["recorded"] == 100 and len(view["entries"]) == 8
    assert json.loads(json.dumps(view)) == view


def test_span_collector_nests_and_restores(enabled_telemetry):
    with telemetry.collect_spans() as outer:
        with telemetry.span("t_fleet_col_a"):
            with telemetry.collect_spans() as inner:
                with telemetry.span("t_fleet_col_b"):
                    pass
            with telemetry.span("t_fleet_col_c"):
                pass
    assert [e["span"] for e in inner.events] == ["t_fleet_col_b"]
    assert [e["span"] for e in outer.events] == ["t_fleet_col_c",
                                                "t_fleet_col_a"]
