"""Host/device tiering (ISSUE 18; docs/index_tiering.md): residency must
be a PURE placement change — tiered search bit-identical to the
fully-resident family search across kinds, dtypes, hot fractions and
ragged cold-chunk tails — plus the zero-compile warmed serving contract
through the tiered ServeEngine backend, the exact-re-rank recall lift on
the PR-3 triage configuration, re-tiering, and the serialization
roundtrip."""

import numpy as np
import pytest

from raft_tpu.core.aot import aot_compile_counters
from raft_tpu.neighbors import ivf_flat, ivf_pq, knn, tiering
from raft_tpu.neighbors.serialize import load_tiered, save_tiered


def make_data(n=3000, dim=32, n_queries=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, (n, dim)).astype(np.float32)
    q = (x[:n_queries] +
         0.01 * rng.normal(0, 1, (n_queries, dim)).astype(np.float32))
    return x, q


def build_index(kind, x):
    if kind == "ivf_flat":
        return ivf_flat.build(ivf_flat.IndexParams(n_lists=32, seed=1), x)
    return ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=8, pq_bits=8,
                                           seed=1), x)


def family_search(kind, index, q, k, n_probes=8):
    mod = ivf_flat if kind == "ivf_flat" else ivf_pq
    return mod.search(mod.SearchParams(n_probes=n_probes), index, q, k)


def assert_same(a, b, msg=""):
    da, ia = np.asarray(a[0]), np.asarray(a[1])
    db, ib = np.asarray(b[0]), np.asarray(b[1])
    assert np.array_equal(ia, ib), f"indices differ {msg}"
    assert np.array_equal(da, db), f"distances differ {msg}"


class TestBitIdentity:
    """Tiered ≡ fully-resident, exactly — the gate the whole residency
    design hangs off (merge order and probe-budget clamps included)."""

    @pytest.mark.parametrize("kind", ["ivf_flat", "ivf_pq"])
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    @pytest.mark.parametrize("hot_fraction", [0.0, 0.5, 1.0])
    def test_grid(self, kind, dtype, hot_fraction):
        x, q = make_data()
        index = build_index(kind, x)
        q = q.astype(dtype)
        full = family_search(kind, index, q, 10)
        # tile_phys=17 forces RAGGED cold tiles (the last tile's chunk
        # count does not divide the cold remainder evenly)
        t = tiering.tier(index, hot_fraction=hot_fraction, tile_phys=17)
        if hot_fraction < 1.0:
            assert len(t.cold_tiles) >= 2
        sp = (ivf_flat if kind == "ivf_flat" else ivf_pq
              ).SearchParams(n_probes=8)
        out = tiering.search(t, q, 10, params=sp)
        assert_same(full, out,
                    f"({kind}, {dtype}, hot={hot_fraction})")

    @pytest.mark.parametrize("kind", ["ivf_flat", "ivf_pq"])
    def test_wide_k_stacked_scan(self, kind):
        # k past _SCAN_STACK_MIN_K rides the stacked one-shot select in
        # scan_probe_lists — residency identity must survive the path
        # change (the refine candidate runs live there)
        x, q = make_data()
        index = build_index(kind, x)
        full = family_search(kind, index, q, 40, n_probes=12)
        t = tiering.tier(index, hot_fraction=0.5, tile_phys=23)
        sp = (ivf_flat if kind == "ivf_flat" else ivf_pq
              ).SearchParams(n_probes=12)
        out = tiering.search(t, q, 40, params=sp)
        assert_same(full, out, f"({kind}, k=40)")

    def test_retier_preserves_results(self):
        x, q = make_data()
        index = build_index("ivf_pq", x)
        full = family_search("ivf_pq", index, q, 10)
        t = tiering.tier(index, hot_fraction=0.25, tile_phys=16)
        s = t.searcher(10, ivf_pq.SearchParams(n_probes=8))
        r0 = tiering.tier_counters.get("retiers", 0)
        t2 = tiering.retier(t, s.hotness(), tile_phys=31)
        assert tiering.tier_counters.get("retiers", 0) == r0 + 1
        out = tiering.search(t2, q, 10, params=ivf_pq.SearchParams(
            n_probes=8))
        assert_same(full, out, "(after retier)")


class TestServing:
    def test_zero_compile_warmed_engine(self):
        from raft_tpu.serve import ServeEngine

        x, q = make_data()
        index = build_index("ivf_pq", x)
        t = tiering.tier(index, hot_fraction=0.5, tile_phys=17,
                         dataset=x)
        sp = ivf_pq.SearchParams(n_probes=8, refine_ratio=4)
        eng = ServeEngine(t, 10, sp, max_batch=128)
        eng.warmup()
        reqs = [q[:40], q[7:19], q[:64]]
        eng.search(reqs)                     # settle any lazy staging
        c0 = aot_compile_counters["compiles"]
        outs = eng.search(reqs)
        assert aot_compile_counters["compiles"] == c0, \
            "warmed tiered serve compiled"
        for j, req in enumerate(reqs):
            solo = tiering.search(t, req, 10, params=sp)
            assert np.array_equal(outs[j][1], np.asarray(solo[1])), j

    def test_refresh_swaps_residency(self):
        from raft_tpu.serve import ServeEngine

        x, q = make_data()
        index = build_index("ivf_pq", x)
        t = tiering.tier(index, hot_fraction=0.25, tile_phys=16)
        sp = ivf_pq.SearchParams(n_probes=8)
        eng = ServeEngine(t, 10, sp, max_batch=128)
        eng.warmup()
        before = eng.search([q[:32]])[0]
        t2 = tiering.retier(t, eng._backend.searcher.hotness(),
                            tile_phys=31)
        eng.refresh(t2, sp)
        eng.warmup()
        after = eng.search([q[:32]])[0]
        assert np.array_equal(before[1], after[1])
        assert np.array_equal(before[0], after[0])


class TestRefine:
    def test_triage_recall_lift(self):
        # the PR-3 triage configuration (3000×32, n_lists=32, pq_dim=8)
        # whose ~0.53 ADC ceiling at k=5/probes=8 is pinned by
        # tests/test_ivf_pq.py's oracle test: refine_ratio=4 at
        # n_probes=16 must lift recall@10 past 0.85 while the unrefined
        # run stays under 0.75 (the lift is real, not a moved baseline)
        x, q = make_data(n_queries=256)
        index = build_index("ivf_pq", x)
        t = tiering.tier(index, hot_fraction=0.5, dataset=x)
        ti = np.asarray(knn(x, q, 10)[1])

        def recall(i):
            return sum(len(set(r.tolist()) & set(g.tolist()))
                       for r, g in zip(np.asarray(i), ti)) / ti.size

        plain = tiering.search(t, q, 10, params=ivf_pq.SearchParams(
            n_probes=16))
        refined = tiering.search(t, q, 10, params=ivf_pq.SearchParams(
            n_probes=16, refine_ratio=4))
        r_plain, r_ref = recall(plain[1]), recall(refined[1])
        assert r_plain <= 0.75, r_plain
        assert r_ref >= 0.85, (r_plain, r_ref)

    def test_pq_refine_requires_dataset(self):
        x, q = make_data()
        index = build_index("ivf_pq", x)
        t = tiering.tier(index, hot_fraction=0.5)   # no dataset
        with pytest.raises(Exception, match="refine"):
            tiering.search(t, q, 10, params=ivf_pq.SearchParams(
                n_probes=8, refine_ratio=4))

    def test_ivf_flat_refine_store_self_builds(self):
        # IVF-Flat reconstructs the refine store from its own packed
        # vectors — refine works without passing the dataset, and exact
        # re-scoring of exact candidates cannot hurt the top-k set
        x, q = make_data()
        index = build_index("ivf_flat", x)
        t = tiering.tier(index, hot_fraction=0.5)
        out = tiering.search(t, q, 10, params=ivf_flat.SearchParams(
            n_probes=8, refine_ratio=2))
        full = family_search("ivf_flat", index, q, 10)
        assert np.array_equal(np.asarray(out[1]), np.asarray(full[1]))


class TestSerialize:
    @pytest.mark.parametrize("kind", ["ivf_flat", "ivf_pq"])
    def test_roundtrip(self, tmp_path, kind):
        x, q = make_data()
        index = build_index(kind, x)
        t = tiering.tier(index, hot_fraction=0.5, tile_phys=17,
                         dataset=x if kind == "ivf_pq" else None)
        path = tmp_path / "tiered"
        save_tiered(path, t)
        t2 = load_tiered(path)
        sp = (ivf_flat if kind == "ivf_flat" else ivf_pq
              ).SearchParams(n_probes=8)
        assert_same(tiering.search(t, q, 10, params=sp),
                    tiering.search(t2, q, 10, params=sp),
                    f"({kind} roundtrip)")
        assert t2.tile_phys == t.tile_phys
        assert len(t2.cold_tiles) == len(t.cold_tiles)
